"""Segment the task-create -> first-ToolCall latency: control plane vs
engine (run on CPU for control-plane numbers, on TPU for the real thing).

Per task: create -> send (reconcile: watch wake, validation, lease, tool
collection) -> engine_done (prefill + constrained generation) -> tc
(toolparse + ToolCall CR create). BASELINE.md's 500 ms p50 target is the
"total" row; `create->send` + `engine_done->tc` is the pure control-plane
share (measured ~19 ms p50 at 16 concurrent tasks on CPU)."""

import asyncio
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LLM, BaseConfig, LLMSpec, TPUProviderConfig,
)
from agentcontrolplane_tpu.engine.engine import Engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.operator import Operator, OperatorOptions

from agentcontrolplane_tpu.testing import make_agent, make_task, setup_with_status

N = 16

import dataclasses

# tiny's max_seq_len (128) would silently clamp max_ctx and tail-truncate
# the rendered agent prompts (truncated prompts also skip the prefix
# cache), so widen it to the serving context
engine = Engine(
    config=dataclasses.replace(PRESETS["tiny"], max_seq_len=512),
    tokenizer=ByteTokenizer(), max_slots=N,
    max_ctx=512, prefill_buckets=(256, 512), decode_block_size=8, seed=0,
)
engine._get_token_table()
engine.start()
engine.prewarm(constrained=True)

marks: dict[str, dict[str, float]] = {}

# instrument the engine client seam
from agentcontrolplane_tpu.engine import client as eng_client

orig_send = eng_client.TPUEngineClient.send_request

async def timed_send(self, messages, tools):
    name = None
    for m in messages:
        if m.role == "user" and m.content.startswith("task "):
            name = "ttft-" + m.content.split()[-1]
    if name and name in marks and "send" not in marks[name]:
        marks[name]["send"] = time.monotonic()
    out = await orig_send(self, messages, tools)
    if name and name in marks and "engine_done" not in marks[name]:
        marks[name]["engine_done"] = time.monotonic()
    return out

eng_client.TPUEngineClient.send_request = timed_send


async def main():
    op = Operator(options=OperatorOptions(
        enable_rest=False, llm_probe=False,
        verify_channel_credentials=False, engine=engine,
    ))
    op.task_reconciler.requeue_delay = 0.02
    op.toolcall_reconciler.poll_interval = 0.02
    store = op.store
    setup_with_status(
        store,
        LLM(metadata=ObjectMeta(name="tpu-llm"),
            spec=LLMSpec(provider="tpu",
                         parameters=BaseConfig(model="tiny", max_tokens=24, temperature=0.7),
                         tpu=TPUProviderConfig(preset="tiny"),
                         provider_config={"tool_choice": "required"})),
        lambda o: (setattr(o.status, "ready", True), setattr(o.status, "status", "Ready")),
    )
    make_agent(store, name="leaf", llm="tpu-llm", system="leaf")
    make_agent(store, name="rooter", llm="tpu-llm", system="use tools", sub_agents=("leaf",))
    await op.start()
    watch = store.watch("ToolCall")
    for i in range(N):
        name = f"ttft-{i}"
        marks[name] = {"create": time.monotonic()}
        make_task(store, name=name, agent="rooter", user_message=f"task {i}")
    deadline = time.monotonic() + 180
    done = 0
    while done < N and time.monotonic() < deadline:
        ev = await watch.next(timeout=deadline - time.monotonic())
        if ev is None:
            break
        if ev.type != "ADDED":
            continue
        tn = ev.object.metadata.labels.get("acp.tpu/task", "")
        if tn in marks and "tc" not in marks[tn]:
            marks[tn]["tc"] = time.monotonic()
            done += 1
    watch.stop()
    await op.stop()

    segs = {"create->send": [], "send->engine_done": [], "engine_done->tc": [],
            "control_plane": [], "total": []}
    for name, m in marks.items():
        if "tc" not in m or "send" not in m:
            continue
        segs["create->send"].append(m["send"] - m["create"])
        segs["send->engine_done"].append(m["engine_done"] - m["send"])
        segs["engine_done->tc"].append(m["tc"] - m["engine_done"])
        # per-task sum, NOT sum of segment medians (p50(a)+p50(b) != p50(a+b))
        segs["control_plane"].append(
            (m["send"] - m["create"]) + (m["tc"] - m["engine_done"])
        )
        segs["total"].append(m["tc"] - m["create"])
    for k, v in segs.items():
        v.sort()
        if v:
            p50 = v[len(v) // 2] * 1e3
            print(f"{k:20s} p50 {p50:8.1f} ms   max {v[-1]*1e3:8.1f} ms   n={len(v)}")


asyncio.run(main())
engine.stop()
