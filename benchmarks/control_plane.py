"""Control-plane throughput: reconciles/sec and status-writes/sec at 64
concurrent Tasks (VERDICT r1 #9 — quantify the sqlite write path so the
kernel doesn't become the bottleneck the reference offloads to etcd).

Runs entirely on CPU with a mock LLM: the measured path is watch -> workqueue
-> reconciler -> CAS status write -> sqlite WAL commit.

    python benchmarks/control_plane.py [--tasks 64] [--sync NORMAL|FULL] [--served]

``--sync FULL`` restores per-commit fsync (etcd-style durability) for an A/B
against the default WAL+NORMAL group-commit behavior. ``--served`` runs the
operator over a RemoteStore (unix socket to a StoreServer owning the sqlite
file) — the multi-replica follower topology — so the socket hop's cost is
measurable against the in-process baseline.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from agentcontrolplane_tpu.kernel.store import SqliteBackend, Store
from agentcontrolplane_tpu.kernel import wait_for
from agentcontrolplane_tpu.llmclient import MockLLMClient, MockLLMClientFactory, assistant
from agentcontrolplane_tpu.operator import Operator, OperatorOptions

from agentcontrolplane_tpu.testing import make_agent, make_llm, make_task


class CountingBackend(SqliteBackend):
    def __init__(self, path: str):
        super().__init__(path)
        self.puts = 0

    def put(self, doc, rv=0):
        self.puts += 1
        super().put(doc, rv)


async def run(n_tasks: int, sync: str, served: bool = False) -> dict:
    tmp = tempfile.mkdtemp(prefix="acp-cpbench-")
    backend = CountingBackend(os.path.join(tmp, "state.db"))
    backend._conn.execute(f"PRAGMA synchronous={sync}")
    local = Store(backend)
    server = None
    if served:
        from agentcontrolplane_tpu.kernel import StoreServer, RemoteStore

        server = StoreServer(local, f"unix://{tmp}/store.sock").start()
        store = RemoteStore(server.address)
    else:
        store = local

    # every request gets a one-turn answer (MockLLMClient falls back to its
    # default when the script is empty)
    mock = MockLLMClient(default=assistant("done"))
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False
        ),
        store=store,
        llm_factory=MockLLMClientFactory(mock),
    )
    op.task_reconciler.requeue_delay = 0.01
    make_llm(store)
    make_agent(store, name="helper")

    await op.start()
    t0 = time.monotonic()
    puts0 = backend.puts
    for i in range(n_tasks):
        make_task(store, name=f"cp-{i}", agent="helper", user_message=f"m{i}")
    for i in range(n_tasks):
        await wait_for(
            store, "Task", f"cp-{i}", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=120,
        )
    elapsed = time.monotonic() - t0
    writes = backend.puts - puts0
    await op.stop()
    if server is not None:
        server.stop()
    return {
        "store": "served" if served else "in-process",
        "sync": sync,
        "tasks": n_tasks,
        "elapsed_s": round(elapsed, 3),
        "tasks_per_s": round(n_tasks / elapsed, 1),
        "status_writes": writes,
        "writes_per_s": round(writes / elapsed, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tasks", type=int, default=64)
    ap.add_argument("--sync", choices=["NORMAL", "FULL"], default="NORMAL")
    ap.add_argument("--served", action="store_true")
    args = ap.parse_args()
    print(json.dumps(asyncio.run(run(args.tasks, args.sync, args.served))), flush=True)


if __name__ == "__main__":
    main()
