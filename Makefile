# Dev workflow (the reference's Makefile orchestrates kind clusters and
# kustomize deploys; standalone TPU-native operation needs only python).

PY ?= python

.PHONY: test test-unit test-e2e test-stress bench run run-multi lint lint-acp \
	chaos-smoke chaos-soak \
	dryrun ci docker-build docker-run observability-up observability-down

IMG ?= acp-tpu:dev
JAX_EXTRA ?=

docker-build:  ## build the operator+engine image (JAX_EXTRA=tpu for TPU VMs)
	docker build -f deploy/Dockerfile --build-arg JAX_EXTRA=$(JAX_EXTRA) -t $(IMG) .

docker-run:  ## serve BASELINE config 1 shape locally (REST on :8080)
	docker run --rm -p 8080:8080 $(IMG)

observability-up:  ## otel-collector + prometheus + grafana (dashboard: ACP-TPU)
	docker compose -f deploy/observability/docker-compose.yaml up -d

observability-down:
	docker compose -f deploy/observability/docker-compose.yaml down

test:
	$(PY) -m pytest tests/ -x -q

test-unit:
	$(PY) -m pytest tests/ -x -q --ignore=tests/e2e

test-e2e:
	$(PY) -m pytest tests/e2e -x -q

test-stress:
	ACP_STRESS=1 $(PY) -m pytest tests/e2e/test_tpu_provider.py -k test_64_concurrent_tasks_stress -x -q

bench:
	$(PY) bench.py

chaos-smoke:  ## one seeded fault cocktail against a live 3-replica fleet, invariants gated (fast CI tier)
	$(PY) -m agentcontrolplane_tpu.cli chaos --seed 3 --gate --replicas 3 --speed 20 \
	  --set n=8 --tpu-preset tiny --tpu-slots 4 --tpu-ctx 64 --tpu-kv-layout paged --no-prewarm

chaos-soak:  ## multi-seed chaos soak + the rest of the slow tier's chaos coverage
	$(PY) -m pytest tests/scenarios/test_chaos.py -q -m slow

dryrun:
	$(PY) -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

run:
	$(PY) -m agentcontrolplane_tpu.cli run --db acp-state.db

run-multi:  ## two-replica dev control plane: owner serves the store, follower joins
	@sh -c '$(PY) -m agentcontrolplane_tpu.cli run --db acp-state.db \
	  --serve-store unix:///tmp/acp-store.sock --identity owner & \
	  owner=$$!; trap "kill $$owner 2>/dev/null" EXIT INT TERM; \
	  sleep 2 && $(PY) -m agentcontrolplane_tpu.cli run \
	  --store unix:///tmp/acp-store.sock --identity follower --port 8083'

lint:
	@if command -v ruff >/dev/null 2>&1; then \
		ruff check agentcontrolplane_tpu tests bench.py; \
	else \
		echo "ruff not installed; falling back to compileall (syntax only)"; \
		$(PY) -m compileall -q agentcontrolplane_tpu tests bench.py; \
	fi

# pinned gates: ACP_LINT_SUPPRESSIONS is the live '# acp-lint: disable='
# count (growth fails with the justification list — raise it only in the
# PR that adds the pragma); ACP_LINT_BUDGET_S bounds the whole pass pack's
# wall time on a bare checkout so a rule can't silently become the slow
# CI step (current full run ~4s; 30s leaves cold-cache headroom).
ACP_LINT_SUPPRESSIONS ?= 4
ACP_LINT_BUDGET_S ?= 30

lint-acp:  ## repo-custom static analysis (acplint) — the engine's correctness contracts
	$(PY) -m agentcontrolplane_tpu.analysis --metrics-docs docs/observability.md \
		--faults-docs \
		--timing --timing-budget $(ACP_LINT_BUDGET_S) \
		--suppression-budget $(ACP_LINT_SUPPRESSIONS) \
		--json acplint-findings.json \
		agentcontrolplane_tpu tests bench.py
	-$(PY) -m agentcontrolplane_tpu.analysis --bench-trend .  # advisory: perf-trajectory sentinel
	-$(PY) -m agentcontrolplane_tpu.analysis --slo-envelopes .  # advisory: scenario SLO envelopes

ci: lint lint-acp test dryrun
