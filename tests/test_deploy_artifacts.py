"""Deploy artifacts stay wired to the code they describe (VERDICT r2 #5/#6).

The container/release/observability files under deploy/ are judged (and
used) as runnable artifacts; these tests pin the cross-references that rot
silently: CLI flags named in the Dockerfile and release manifests, metric
names queried by the Grafana dashboard, and plain parseability of every
YAML/JSON in the tree.
"""

from __future__ import annotations

import json
import pathlib
import re

import yaml

REPO = pathlib.Path(__file__).parent.parent
DEPLOY = REPO / "deploy"


def test_all_deploy_yaml_parses():
    paths = list(DEPLOY.rglob("*.yaml")) + list(DEPLOY.rglob("*.yml"))
    assert len(paths) >= 6
    for p in paths:
        docs = list(yaml.safe_load_all(p.read_text()))
        assert docs, p


def _deployment_images(path: pathlib.Path) -> list[str]:
    return [
        c["image"]
        for d in yaml.safe_load_all(path.read_text())
        if d and d.get("kind") == "Deployment"
        for c in d["spec"]["template"]["spec"]["containers"]
    ]


def test_release_bundles_exist_and_pin_the_image():
    """EVERY versioned bundle pins its own tag (the reference ships one
    manifest per release, acp/config/release/v*.yaml)."""
    versioned = sorted((DEPLOY / "release").glob("v*.yaml"))
    assert len(versioned) >= 2  # history accumulates; releases are not rewritten
    for path in versioned:
        tag = ":" + path.stem
        images = _deployment_images(path)
        assert images and all(tag in i for i in images), path
    images = _deployment_images(DEPLOY / "release" / "latest.yaml")
    assert images and all(":latest" in i for i in images)


def test_current_version_has_a_release_bundle_and_latest_mirrors_it():
    """Lockstep: __version__ must have deploy/release/v<version>.yaml, and
    latest.yaml must be that bundle with only the image tag changed."""
    from agentcontrolplane_tpu import __version__

    current = DEPLOY / "release" / f"v{__version__}.yaml"
    assert current.exists(), f"no release bundle for __version__={__version__}"
    expected_latest = current.read_text().replace(f"v{__version__}", "latest")
    assert (DEPLOY / "release" / "latest.yaml").read_text() == expected_latest


def _cli_flags() -> set[str]:
    src = (REPO / "agentcontrolplane_tpu" / "cli.py").read_text()
    return set(re.findall(r'"(--[a-z][a-z0-9-]*)"', src))


def test_dockerfile_cmd_flags_exist_in_cli():
    text = (DEPLOY / "Dockerfile").read_text()
    m = re.search(r'CMD \[(.*?)\]', text)
    assert m
    args = json.loads("[" + m.group(1) + "]")
    flags = {a for a in args if a.startswith("--")}
    missing = flags - _cli_flags()
    assert not missing, f"Dockerfile CMD uses unknown CLI flags: {missing}"


def test_release_manifest_args_exist_in_cli():
    flags = _cli_flags()
    for path in (DEPLOY / "release").glob("*.yaml"):
        for doc in yaml.safe_load_all(path.read_text()):
            if not doc or doc.get("kind") != "Deployment":
                continue
            for c in doc["spec"]["template"]["spec"]["containers"]:
                for arg in c.get("args", []):
                    if arg.startswith("--"):
                        flag = arg.split("=", 1)[0]
                        assert flag in flags, (
                            f"{path.name} uses unknown flag {flag}"
                        )


def _emitted_metric_names() -> set[str]:
    names: set[str] = set()
    for p in (REPO / "agentcontrolplane_tpu").rglob("*.py"):
        names.update(re.findall(r'"(acp_[a-z0-9_]+)"', p.read_text()))
    return names


def test_dashboard_queries_reference_emitted_metrics():
    dash = json.loads(
        (DEPLOY / "observability" / "grafana" / "dashboards" / "acp-tpu.json").read_text()
    )
    emitted = _emitted_metric_names()
    exprs = [
        t["expr"] for panel in dash["panels"] for t in panel.get("targets", [])
    ]
    assert len(exprs) >= 10
    for expr in exprs:
        for name in re.findall(r"\bacp_[a-z0-9_]+", expr):
            base = re.sub(r"_(count|sum|bucket)$", "", name)
            assert base in emitted, f"dashboard queries unknown metric {name}"


def test_dashboard_panels_cover_the_required_views():
    """VERDICT r2 #6: tok/s, TTFT, slot occupancy, prefix-cache hits, task
    phases must all be on the dashboard."""
    dash = json.loads(
        (DEPLOY / "observability" / "grafana" / "dashboards" / "acp-tpu.json").read_text()
    )
    all_exprs = " ".join(
        t["expr"] for p in dash["panels"] for t in p.get("targets", [])
    )
    for required in (
        "acp_engine_tokens_total",
        "acp_engine_ttft_seconds",
        "acp_engine_active_slots",
        "acp_engine_prefix_cache_hit_requests",
        "acp_objects",
        "acp_reconcile_total",
    ):
        assert required in all_exprs, f"dashboard missing {required}"


def test_compose_mounts_every_config_it_references():
    compose = yaml.safe_load(
        (DEPLOY / "observability" / "docker-compose.yaml").read_text()
    )
    for svc in compose["services"].values():
        for vol in svc.get("volumes", []):
            host = vol.split(":", 1)[0]
            assert (DEPLOY / "observability" / host).exists(), f"missing {host}"


def test_prometheus_scrapes_operator_and_collector():
    prom = yaml.safe_load((DEPLOY / "observability" / "prometheus.yml").read_text())
    jobs = {j["job_name"] for j in prom["scrape_configs"]}
    assert {"acp-tpu", "otel-collector"} <= jobs
