"""Routing-policy edge tests against fake engines (no device work):
affinity hit routes hot, cold persona routes least-loaded, shed replicas
are skipped, round-robin cycles, dead-marker errors fail over, and the
exactly-once stream dedup counters."""

from __future__ import annotations

from concurrent.futures import Future
from types import SimpleNamespace

import pytest

from agentcontrolplane_tpu.engine.engine import (
    EngineOverloadedError,
    SamplingParams,
)
from agentcontrolplane_tpu.fleet import FleetRouter, persona_affinity_key
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.testing import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


class FakeTokenizer:
    def encode(self, text):
        return list(text.encode())

    def decode(self, tokens):
        return bytes(tokens).decode(errors="replace")


class FakeEngine:
    """Engine-shaped stub: submit() resolves per the scripted behavior —
    "ok" (greedy-deterministic fake tokens), "shed", "crash", or "hold"
    (leave the future pending for queued-work tests)."""

    def __init__(self, behavior="ok", waiting=0, active=0, goodput=1.0):
        self.behavior = behavior
        self.waiting = waiting
        self.active = active
        self.goodput = goodput
        self.tokenizer = FakeTokenizer()
        self.submissions = []
        self.held = []

    def ensure_running(self):
        return True

    def cancel(self, future):
        future.cancel()

    def submit(self, prompt, sampling=None, on_tokens=None, timeout_s=None,
               on_tool_call=None, park=False, trace=None, export_kv=False):
        self.submissions.append(list(prompt))
        fut = Future()
        fut.rid = f"fake-{len(self.submissions)}"
        fut.admitted = Future()
        fut.early_tool_calls = []
        if self.behavior == "shed":
            fut.set_exception(
                EngineOverloadedError("fake shed", retry_after_s=7.0)
            )
        elif self.behavior == "crash":
            fut.set_exception(RuntimeError("engine crashed: fake"))
        elif self.behavior == "hold":
            self.held.append((fut, list(prompt), on_tokens))
        else:
            fut.admitted.set_result(True)
            tokens = [t ^ 1 for t in prompt][:8]
            if on_tokens is not None:
                on_tokens(tokens)
            fut.set_result(SimpleNamespace(
                text=self.tokenizer.decode(tokens), tokens=tokens,
                finish_reason="stop", kv_handoff=None,
            ))
        return fut

    def stats(self):
        return {
            "waiting": self.waiting,
            "active_slots": self.active,
            "prefilling_slots": 0,
            "perf": {"goodput": {"ratio": self.goodput}},
        }


def make_router(*engines, policy="affinity", **kw):
    router = FleetRouter(store=Store(), policy=policy,
                         heartbeat_interval=60.0, **kw)
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng)
    return router


def test_persona_affinity_key_hashes_system_prompt():
    objs = [SimpleNamespace(role="system", content="be terse"),
            SimpleNamespace(role="user", content="hi")]
    dicts = [{"role": "system", "content": "be terse"},
             {"role": "user", "content": "hi"}]
    assert persona_affinity_key(objs) == persona_affinity_key(dicts)
    # different persona, different home
    assert persona_affinity_key(objs) != persona_affinity_key(
        [{"role": "system", "content": "be verbose"}]
    )
    # no system message: first message stands in
    assert persona_affinity_key([{"role": "user", "content": "hi"}]) == \
        persona_affinity_key([{"role": "user", "content": "hi"}])


def test_affinity_hit_routes_to_hot_replica():
    e0, e1 = FakeEngine(), FakeEngine()
    router = make_router(e0, e1)
    try:
        router.submit("hello", SamplingParams(), affinity_key="persona-a"
                      ).result(timeout=5)
        first = e0 if e0.submissions else e1
        for _ in range(3):
            router.submit("hello again", SamplingParams(),
                          affinity_key="persona-a").result(timeout=5)
        # every same-persona turn landed on the first home
        other = e1 if first is e0 else e0
        assert len(first.submissions) == 4 and not other.submissions
        assert router.affinity_hits == 3 and router.affinity_misses == 1
    finally:
        router.stop()


def test_cold_persona_routes_least_loaded():
    loaded = FakeEngine(waiting=5, active=3)
    idle = FakeEngine(waiting=0, active=0)
    router = make_router(loaded, idle)
    try:
        router.submit("x", SamplingParams(), affinity_key="cold").result(timeout=5)
        assert idle.submissions and not loaded.submissions
        assert router.affinity_misses == 1
        # the miss re-homed the key: next turn is a hit on the same replica
        router.submit("y", SamplingParams(), affinity_key="cold").result(timeout=5)
        assert len(idle.submissions) == 2 and router.affinity_hits == 1
    finally:
        router.stop()


def test_goodput_breaks_load_ties():
    slow = FakeEngine(goodput=0.4)
    fast = FakeEngine(goodput=0.9)
    router = make_router(slow, fast)
    try:
        router.submit("x", SamplingParams(), affinity_key="k").result(timeout=5)
        assert fast.submissions and not slow.submissions
    finally:
        router.stop()


def test_shed_replica_skipped_pool_absorbs():
    shedder = FakeEngine(behavior="shed")
    ok = FakeEngine(waiting=9, active=9)  # worse-loaded, but serving
    router = make_router(shedder, ok)
    try:
        # home the persona on the shedder, then watch the skip
        router._affinity["p"] = "r0"
        result = router.submit("hello", SamplingParams(),
                               affinity_key="p").result(timeout=5)
        assert result.finish_reason == "stop"
        assert ok.submissions and router.sheds_skipped == 1
    finally:
        router.stop()


def test_pool_wide_shed_propagates_retry_after():
    router = make_router(FakeEngine(behavior="shed"), FakeEngine(behavior="shed"))
    try:
        fut = router.submit("hello", SamplingParams(), affinity_key="p")
        with pytest.raises(EngineOverloadedError) as ei:
            fut.result(timeout=5)
        assert "fleet replicas shed" in str(ei.value)
        assert ei.value.retry_after_s == 7.0  # the replicas' own backoff
    finally:
        router.stop()


def test_round_robin_cycles_replicas():
    e0, e1 = FakeEngine(), FakeEngine()
    router = make_router(e0, e1, policy="round_robin")
    try:
        for _ in range(4):
            router.submit("x", SamplingParams()).result(timeout=5)
        assert len(e0.submissions) == 2 and len(e1.submissions) == 2
        assert router.affinity_hits == 0  # policy never consults the map
    finally:
        router.stop()


def test_dead_marker_fails_over_and_adopts_lease():
    dying = FakeEngine(behavior="crash")
    survivor = FakeEngine()
    router = make_router(dying, survivor)
    try:
        router._affinity["p"] = "r0"
        result = router.submit("hello", SamplingParams(),
                               affinity_key="p").result(timeout=5)
        assert result.finish_reason == "stop" and survivor.submissions
        assert router.failovers == 1
        r0 = router.pool.get("r0")
        assert not r0.alive
        # the survivor adopted the dead lease under a bumped epoch
        assert router.pool.lease_holder(r0).endswith("/r1")
        # the dead replica's affinity homes were evicted, then re-homed
        assert router._affinity["p"] == "r1"
    finally:
        router.stop()


def test_failover_budget_exhaustion_propagates():
    router = make_router(FakeEngine(behavior="crash"),
                         FakeEngine(behavior="crash"), failover_max=2)
    try:
        fut = router.submit("hello", SamplingParams(), affinity_key="p")
        with pytest.raises(RuntimeError, match="engine crashed|no live replicas"):
            fut.result(timeout=5)
        assert not router.pool.alive()
    finally:
        router.stop()


def test_route_stale_fault_evicts_and_rehomes():
    e0, e1 = FakeEngine(), FakeEngine()
    router = make_router(e0, e1)
    try:
        router.submit("x", SamplingParams(), affinity_key="p").result(timeout=5)
        FAULTS.arm("fleet.route_stale", times=1)
        router.submit("y", SamplingParams(), affinity_key="p").result(timeout=5)
        # the forced-stale turn counted as a miss, not a hit
        assert router.affinity_hits == 0 and router.affinity_misses == 2
        # ...and the next turn is a clean hit again
        router.submit("z", SamplingParams(), affinity_key="p").result(timeout=5)
        assert router.affinity_hits == 1
    finally:
        router.stop()


def test_exactly_once_stream_dedup_across_failover():
    """A failed-over stream must deliver each token exactly once: the
    retry regenerates the full (deterministic) output and the router
    suppresses the prefix the caller already saw."""
    class CrashMidStream(FakeEngine):
        def submit(self, prompt, sampling=None, on_tokens=None, **kw):
            self.submissions.append(list(prompt))
            fut = Future()
            fut.rid = "crash-mid"
            fut.admitted = Future()
            fut.early_tool_calls = []
            fut.admitted.set_result(True)
            if on_tokens is not None:
                on_tokens([10, 11, 12])  # streamed, then the replica dies
            fut.set_exception(RuntimeError("engine crashed: fake"))
            return fut

    class Survivor(FakeEngine):
        def submit(self, prompt, sampling=None, on_tokens=None, **kw):
            self.submissions.append(list(prompt))
            fut = Future()
            fut.rid = "retry"
            fut.admitted = Future()
            fut.early_tool_calls = []
            fut.admitted.set_result(True)
            full = [10, 11, 12, 13, 14]  # greedy replay: same prefix
            if on_tokens is not None:
                on_tokens(full[:2])
                on_tokens(full[2:])
            fut.set_result(SimpleNamespace(
                text="", tokens=full, finish_reason="stop", kv_handoff=None))
            return fut

    streamed = []
    router = make_router(CrashMidStream(), Survivor())
    try:
        router._affinity["p"] = "r0"
        fut = router.submit("hello", SamplingParams(), affinity_key="p",
                            on_tokens=streamed.extend)
        result = fut.result(timeout=5)
        assert result.tokens == [10, 11, 12, 13, 14]
        assert streamed == [10, 11, 12, 13, 14]  # no replayed duplicates
        assert router.failovers == 1
    finally:
        router.stop()


def test_stats_shape_and_fleet_gauge():
    from agentcontrolplane_tpu.observability.metrics import REGISTRY

    router = make_router(FakeEngine(waiting=2, active=1, goodput=0.5),
                         FakeEngine())
    try:
        router.submit("x", SamplingParams(), affinity_key="p").result(timeout=5)
        doc = router.stats()
        assert {r["id"] for r in doc["replicas"]} == {"r0", "r1"}
        row = next(r for r in doc["replicas"] if r["id"] == "r0")
        assert row["queue_depth"] == 2 and row["active_slots"] == 1
        assert row["lease"]["holder"] == router.pool.identity
        assert doc["routing"]["routed"] == 1
        assert doc["failover"]["failover_max"] == router.failover_max
        assert doc["handoff"]["enabled"] is False
        gauge = REGISTRY._metrics.get("acp_fleet_replicas")
        assert gauge is not None and gauge.values.get(()) == 2.0
    finally:
        router.stop()
