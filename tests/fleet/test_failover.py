"""Acceptance: a 2-replica pool under ``fleet.replica_crash`` completes
every in-flight AND queued request exactly-once with output byte-identical
to an uncrashed single-engine baseline — with the invariant checker armed
on all replicas."""

from __future__ import annotations

import dataclasses

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import PRESETS, Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256,
                          n_kv_heads=2)


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout="paged",
        page_size=8, **kw,
    )
    eng.start()
    return eng


def make_pool(n=2, **router_kw):
    router = FleetRouter(store=Store(), heartbeat_interval=60.0, **router_kw)
    engines = [make_engine() for _ in range(n)]
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng)
    return router, engines


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def teardown_pool(router, engines, extra=()):
    router.stop()
    for eng in list(engines) + list(extra):
        try:
            eng.stop()
        except Exception:
            pass


def test_crash_mid_decode_fails_over_byte_identical():
    """The tentpole guarantee: crash the affinity-homed replica MID-DECODE
    (deterministic per-replica fault), and the caller still receives one
    contiguous stream, byte-identical to an uncrashed single engine."""
    router, engines = make_pool(2)
    baseline = make_engine()
    try:
        prompt = "tell me about the fleet tier"
        sp = SamplingParams(temperature=0.0, max_tokens=24)
        # home the persona, then kill its replica two decode steps in
        router.submit("warm " + prompt, SamplingParams(temperature=0.0,
                      max_tokens=2), affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        FAULTS.arm("fleet.replica_crash", times=1, after_steps=1,
                   replica=target)
        streamed = []
        fut = router.submit(prompt, sp, affinity_key="p",
                            on_tokens=streamed.extend)
        result = fut.result(timeout=180)
        expected = baseline.submit(prompt, sp).result(timeout=120)
        assert result.text == expected.text
        assert result.tokens == expected.tokens
        # exactly-once: the stream is the result, no replayed prefix
        assert streamed == list(result.tokens)
        assert router.failovers == 1
        dead = router.pool.get(target)
        assert not dead.alive
        survivor = router.pool.alive()[0]
        # fencing trace: the survivor adopted the dead lease (epoch > 1)
        assert router.pool.lease_holder(dead).endswith("/" + survivor.id)
    finally:
        teardown_pool(router, engines, extra=[baseline])


def test_crash_completes_inflight_and_queued_exactly_once():
    """All work on the dying replica — the in-flight request AND the ones
    still queued behind it — fails over and completes byte-identical to
    baselines; the survivor's invariant checker stays green throughout."""
    router, engines = make_pool(2)
    baseline = make_engine()
    try:
        prompts = [f"queued request number {i} says hello" for i in range(5)]
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        router.submit("warm the persona", SamplingParams(temperature=0.0,
                      max_tokens=2), affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        FAULTS.arm("fleet.replica_crash", times=1, after_steps=2,
                   replica=target)
        futures = [router.submit(p, sp, affinity_key="p") for p in prompts]
        results = [f.result(timeout=180) for f in futures]
        for prompt, result in zip(prompts, results):
            expected = baseline.submit(prompt, sp).result(timeout=120)
            assert result.text == expected.text, prompt
        assert router.failovers >= 1
        assert len(router.pool.alive()) == 1
        # the survivor served everything with its invariant checker armed
        # (a bookkeeping break would have crashed its loop); it still serves
        follow = router.submit("one more after the storm", sp,
                               affinity_key="p").result(timeout=120)
        assert follow.finish_reason in ("stop", "length")
    finally:
        teardown_pool(router, engines, extra=[baseline])


def test_crash_failover_under_page_pressure_stress():
    """Satellite stress: replica crash mid-decode while the survivor runs
    under page pressure (halved KV pool -> preempt/swap churn), armed
    invariants on every replica. Failed-over outputs must STILL match the
    uncontended baseline byte-for-byte."""
    router, engines = make_pool(2)
    baseline = make_engine()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        router.submit("warm the persona", SamplingParams(temperature=0.0,
                      max_tokens=2), affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        FAULTS.arm("fleet.replica_crash", times=1, after_steps=1,
                   replica=target)
        FAULTS.arm("engine.page_pressure", pages=8)  # squeeze every pool
        prompts = [f"stress request {i} under pressure" for i in range(4)]
        futures = [router.submit(p, sp, affinity_key="p") for p in prompts]
        results = [f.result(timeout=240) for f in futures]
        FAULTS.disarm("engine.page_pressure")
        for prompt, result in zip(prompts, results):
            expected = baseline.submit(prompt, sp).result(timeout=120)
            assert result.text == expected.text, prompt
        assert router.failovers >= 1 and len(router.pool.alive()) == 1
    finally:
        teardown_pool(router, engines, extra=[baseline])


def test_router_ensure_running_never_revives_dead_replica():
    router, engines = make_pool(2)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        router.submit("hi", sp, affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        FAULTS.arm("fleet.replica_crash", times=1, replica=target)
        router.submit("hello there", sp, affinity_key="p").result(timeout=180)
        assert not router.pool.get(target).alive
        assert router.ensure_running() is True  # the survivor serves
        assert not router.pool.get(target).alive  # and the dead stay dead
    finally:
        teardown_pool(router, engines)
