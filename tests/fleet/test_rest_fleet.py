"""``GET /v1/fleet`` — the router's stats payload through the REST front
door, and the 503 posture for single-engine deployments."""

from __future__ import annotations

import asyncio
from concurrent.futures import Future
from types import SimpleNamespace

import aiohttp

from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.llmclient import MockLLMClient, MockLLMClientFactory
from agentcontrolplane_tpu.operator import Operator, OperatorOptions


class _StubEngine:
    def __init__(self):
        self.tokenizer = SimpleNamespace(
            encode=lambda s: list(s.encode()),
            decode=lambda toks: bytes(toks).decode(errors="replace"),
        )

    def ensure_running(self):
        return True

    def cancel(self, future):
        future.cancel()

    def submit(self, prompt, sampling=None, on_tokens=None, timeout_s=None,
               on_tool_call=None, park=False, trace=None, export_kv=False):
        fut = Future()
        fut.rid = "stub"
        fut.admitted = Future()
        fut.admitted.set_result(True)
        fut.early_tool_calls = []
        fut.set_result(SimpleNamespace(text="ok", tokens=[1],
                                       finish_reason="stop", kv_handoff=None))
        return fut

    def stats(self):
        return {"waiting": 1, "active_slots": 2, "prefilling_slots": 0,
                "perf": {"goodput": {"ratio": 0.75}}}


class FleetHarness:
    def __init__(self, fleet=None):
        self.operator = Operator(
            options=OperatorOptions(
                enable_rest=True, api_port=0, llm_probe=False,
                verify_channel_credentials=False, fleet=fleet,
            ),
            llm_factory=MockLLMClientFactory(MockLLMClient()),
        )

    async def __aenter__(self):
        await self.operator.start()
        for _ in range(100):
            if self.operator.rest_server.bound_port:
                break
            await asyncio.sleep(0.02)
        self.base = f"http://127.0.0.1:{self.operator.rest_server.bound_port}"
        self.http = aiohttp.ClientSession()
        return self

    async def __aexit__(self, *exc):
        await self.http.close()
        await self.operator.stop()


async def test_fleet_endpoint_serves_router_stats():
    router = FleetRouter(store=Store(), heartbeat_interval=60.0)
    router.add_replica("r0", _StubEngine())
    router.add_replica("r1", _StubEngine())
    try:
        async with FleetHarness(fleet=router) as h:
            resp = await h.http.get(f"{h.base}/v1/fleet")
            assert resp.status == 200
            doc = await resp.json()
            assert doc["configured"] is True
            assert {r["id"] for r in doc["replicas"]} == {"r0", "r1"}
            row = doc["replicas"][0]
            assert row["alive"] is True
            assert row["lease"]["holder"] == router.pool.identity
            assert row["queue_depth"] == 1 and row["goodput_ratio"] == 0.75
            for block in ("routing", "failover", "handoff"):
                assert block in doc
    finally:
        router.stop()


async def test_fleet_endpoint_503_without_router():
    async with FleetHarness() as h:
        resp = await h.http.get(f"{h.base}/v1/fleet")
        assert resp.status == 503
        doc = await resp.json()
        assert "no fleet router" in doc["error"]


async def test_scrape_refresh_fleet_gauges_agree_with_router_stats():
    """Satellite (ISSUE 17, extending the ISSUE 12 drift gate): every
    fleet-side gauge the scrape path refreshes must agree with
    FleetRouter.stats() — a gauge whose scrape-time refresh reads a
    different field than /v1/fleet serves would silently fork the
    dashboard from the API."""
    import re as _re

    router = FleetRouter(store=Store(), heartbeat_interval=60.0)
    router.add_replica("r0", _StubEngine())
    router.add_replica("r1", _StubEngine())
    try:
        # route traffic with an affinity key so the gauges have signal
        for i in range(3):
            router.submit(f"drift probe {i}", affinity_key="persona-a")\
                .result(timeout=10)
        async with FleetHarness(fleet=router) as h:
            text = await (await h.http.get(f"{h.base}/metrics")).text()
            fs = router.stats()

            def gauge(name: str) -> float:
                m = _re.search(rf"^{name} (\S+)$", text, _re.M)
                assert m, f"{name} missing from /metrics"
                return float(m.group(1))

            rows = fs["replicas"]
            assert gauge("acp_fleet_replicas") == float(
                sum(1 for r in rows if r["alive"])
            ) == 2.0
            assert gauge("acp_fleet_inflight") == float(
                fs["routing"]["inflight"]
            )
            assert gauge("acp_fleet_affinity_keys") == float(
                fs["routing"]["affinity_keys"]
            ) >= 1.0
            assert gauge("acp_fleet_queue_depth") == float(
                sum(r["queue_depth"] or 0 for r in rows)
            )
    finally:
        router.stop()
