"""fleet/health.py: the per-replica gray-failure state machine — pure
hysteresis unit tests plus the router-integration path (a stalling
replica degrades, sheds its affinity homes, stops winning new ones, and
recovers once the throttle lifts)."""

from __future__ import annotations

import dataclasses
import time

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import PRESETS, Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.fleet.health import (
    DEAD,
    DEGRADED,
    HEALTHY,
    HealthPolicy,
    HealthSample,
    ReplicaHealth,
)
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256,
                          n_kv_heads=2)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- state machine (no engine) ------------------------------------------------


def test_degrade_needs_consecutive_bad_samples():
    """Hysteresis: one stall burst never flips routing; degrade_after
    consecutive bad samples do, with the reason in the ledger."""
    m = ReplicaHealth("r0", HealthPolicy(degrade_after=2))
    assert m.observe(HealthSample(stalls=0)) is None   # baseline sample
    assert m.observe(HealthSample(stalls=1)) is None   # bad #1
    assert m.observe(HealthSample(stalls=1)) is None   # clean: streak resets
    assert m.observe(HealthSample(stalls=2)) is None   # bad #1 again
    assert m.state == HEALTHY
    assert m.observe(HealthSample(stalls=3)) == DEGRADED  # bad #2
    idx, frm, to, reason = m.transitions[-1]
    assert (frm, to) == (HEALTHY, DEGRADED)
    assert "stalls+1" in reason


def test_recovery_hysteresis_and_ledger():
    m = ReplicaHealth("r0", HealthPolicy(degrade_after=1, recover_after=3))
    m.observe(HealthSample(stalls=0))
    assert m.observe(HealthSample(stalls=5)) == DEGRADED
    # two clean samples, then a relapse: the good streak resets
    assert m.observe(HealthSample(stalls=5)) is None
    assert m.observe(HealthSample(stalls=5)) is None
    assert m.observe(HealthSample(stalls=6)) is None  # bad (already degraded)
    assert m.state == DEGRADED
    for _ in range(2):
        assert m.observe(HealthSample(stalls=6)) is None
    assert m.observe(HealthSample(stalls=6)) == HEALTHY
    assert [(frm, to) for _, frm, to, _ in m.transitions] == [
        (HEALTHY, DEGRADED), (DEGRADED, HEALTHY),
    ]
    assert m.transitions[-1][3] == "recovered"


def test_queue_trend_and_goodput_signals():
    pol = HealthPolicy(degrade_after=1, queue_trend_len=2, queue_min=4,
                       goodput_floor=0.5)
    m = ReplicaHealth("r0", pol)
    # strictly-growing depth below queue_min never counts
    for depth in (0, 1, 2, 3):
        assert m.observe(HealthSample(queue_depth=depth)) is None
    # ...but crossing queue_min with the streak going trips the trend
    assert m.observe(HealthSample(queue_depth=5)) == DEGRADED
    assert "queue_trend:5" in m.transitions[-1][3]

    m2 = ReplicaHealth("r1", pol)
    # a starved goodput ratio only counts while work is queued
    assert m2.observe(HealthSample(queue_depth=0, goodput_ratio=0.1)) is None
    assert m2.observe(HealthSample(queue_depth=2, goodput_ratio=0.1)) == DEGRADED
    assert "goodput:0.10" in m2.transitions[-1][3]


def test_dead_is_terminal():
    m = ReplicaHealth("r0", HealthPolicy(recover_after=1))
    assert m.observe(HealthSample(alive=False)) == DEAD
    assert m.transitions[-1][3] == "lease"
    # observation never resurrects: re-registration is an operator act
    for _ in range(5):
        assert m.observe(HealthSample()) is None
    assert m.state == DEAD
    assert m.mark_dead() is None  # idempotent mirror


def test_replayed_sample_stream_reproduces_ledger():
    """The judgment is a pure function of the sample stream — the chaos
    conductor's determinism story depends on this."""
    stream = [
        HealthSample(stalls=0), HealthSample(stalls=2),
        HealthSample(stalls=4), HealthSample(queue_depth=3, stalls=4),
        HealthSample(stalls=4), HealthSample(stalls=4),
        HealthSample(stalls=4), HealthSample(stalls=4),
        HealthSample(alive=False),
    ]
    a = ReplicaHealth("r0")
    b = ReplicaHealth("r0")
    for s in stream:
        a.observe(s)
    for s in stream:
        b.observe(s)
    assert a.transitions == b.transitions
    assert [(frm, to) for _, frm, to, _ in a.transitions] == [
        (HEALTHY, DEGRADED),   # two stall deltas back to back
        (DEGRADED, HEALTHY),   # four clean samples recover
        (HEALTHY, DEAD),       # lease loss is terminal
    ]
    assert a.transitions[-1][3] == "lease"


# -- router integration -------------------------------------------------------


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout="paged",
        page_size=8, **kw,
    )
    eng.start()
    return eng


def teardown_pool(router, engines):
    router.stop()
    for eng in engines:
        try:
            eng.stop()
        except Exception:
            pass


def _wait_for(predicate, timeout_s=30.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def test_stalling_replica_degrades_sheds_affinity_and_recovers():
    """The tentpole integration path: ``engine.slow_cycle`` pinned to the
    affinity-homed replica trips the stall watchdog; the health machine
    degrades it within a couple of watchdog ticks; its persona keys are
    shed and NEW homes land on the healthy replica; once the throttle
    budget drains, clean samples recover it."""
    router = FleetRouter(
        store=Store(), heartbeat_interval=60.0,
        # >= the engines' stall cadence (stall_min_s=0.02 + 0.08 throttle)
        # so consecutive watchdog samples each see a fresh stall delta
        watchdog_interval_s=0.1,
        health_policy=HealthPolicy(degrade_after=2, recover_after=3),
    )
    engines = [make_engine(stall_mult=2.0, stall_min_s=0.02)
               for _ in range(2)]
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=2)
        # enough post-compile cycles to settle the target's cadence floor
        # (the stall baseline) before the throttle lands
        router.submit("warm the persona", SamplingParams(temperature=0.0,
                      max_tokens=16), affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        FAULTS.arm("engine.slow_cycle", times=12, delay_s=0.08,
                   replica=target)
        # keep the gray replica's scheduler busy so cycles (and stalls)
        # actually happen while the throttle budget drains
        slow = router.submit(
            "ride the gray replica", SamplingParams(temperature=0.0,
                                                    max_tokens=24),
            affinity_key="p",
        )
        assert _wait_for(lambda: router._health_state(target) == DEGRADED), \
            "stalling replica never degraded"
        # leaving healthy shed the re-homeable keys...
        assert "p" not in router._affinity
        # ...and a NEW home must land on the healthy survivor
        other = [r.id for r in router.pool.replicas() if r.id != target][0]
        router.submit("home a fresh persona", sp,
                      affinity_key="q").result(timeout=120)
        assert router._affinity["q"] == other
        slow.result(timeout=180)
        # throttle budget drained: clean samples recover the replica
        assert _wait_for(lambda: router._health_state(target) == HEALTHY), \
            "replica never recovered after the throttle lifted"
        stats = router.stats()
        by_id = {r["id"]: r for r in stats["replicas"]}
        assert by_id[target]["stalls"] > 0
        assert by_id[target]["health"] == HEALTHY
        assert stats["health"]["transitions"] >= 2
    finally:
        teardown_pool(router, engines)


def test_dead_replica_mirrors_into_health_ledger():
    """The lease/error path owns death; the monitor mirrors it (gauge,
    ledger) and the state is terminal."""
    router = FleetRouter(store=Store(), heartbeat_interval=60.0,
                         watchdog_interval_s=0.05)
    engines = [make_engine() for _ in range(2)]
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        router.submit("warm the persona", SamplingParams(temperature=0.0,
                      max_tokens=2), affinity_key="p0").result(timeout=120)
        target = router._affinity["p0"]
        survivor = [r.id for r in router.pool.replicas()
                    if r.id != target][0]
        FAULTS.arm("fleet.replica_crash", times=1, after_steps=1,
                   replica=target)
        router.submit("crash the homed replica", sp,
                      affinity_key="p0").result(timeout=180)
        assert _wait_for(lambda: router._health_state(target) == DEAD)
        assert router._health_state(survivor) == HEALTHY
    finally:
        teardown_pool(router, engines)
