"""Acceptance: the disaggregated prefill->decode handoff restores KV
bit-exactly — greedy output byte-identical to a full local prefill — in
both KV layouts, quantized on and off, with the invariant checker armed;
and every failure path (``fleet.handoff_error``, prompt below the cut
floor) degrades to a full local prefill with identical output."""

from __future__ import annotations

import dataclasses

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import PRESETS, Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256,
                          n_kv_heads=2)
PROMPT = "a prompt long enough to cross several pages of kv!"
SP = SamplingParams(temperature=0.0, max_tokens=12)


def make_engine(kv_layout="paged", quantize_kv=False, **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    kw.setdefault("host_kv_bytes", 1 << 20)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout=kv_layout,
        page_size=8, quantize_kv=quantize_kv, **kw,
    )
    eng.start()
    return eng


def make_disagg_pool(kv_layout="paged", quantize_kv=False):
    router = FleetRouter(store=Store(), handoff_min_tokens=8,
                         heartbeat_interval=60.0)
    prefill = make_engine(kv_layout, quantize_kv)
    decode = make_engine(kv_layout, quantize_kv)
    router.add_replica("pf", prefill, role="prefill")
    router.add_replica("dc", decode, role="decode")
    return router, prefill, decode


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def teardown(router, *engines):
    router.stop()
    for eng in engines:
        try:
            eng.stop()
        except Exception:
            pass


@pytest.mark.parametrize(
    "kv_layout,quantize_kv",
    [("paged", False), ("paged", True), ("slot", False), ("slot", True)],
)
def test_disaggregated_handoff_bit_exact(kv_layout, quantize_kv):
    """The KV that crossed the wire must be the KV local prefill would
    have written: the decode replica (restored KV) and the prefill
    replica (its own locally-written KV, decoded directly) must emit
    byte-identical greedy tokens. A corrupt transfer — wrong scales,
    misaligned cut, layout mismatch — diverges immediately."""
    router, prefill, decode = make_disagg_pool(kv_layout, quantize_kv)
    try:
        result = router.submit(PROMPT, SP, affinity_key="p").result(timeout=180)
        assert router.handoffs == 1 and router.handoff_errors == 0
        assert router.handoff_bytes > 0
        assert decode.kv_injects == 1
        # local decode over the prefill replica's OWN slot KV (prefix-
        # cache hit on the leg it just ran) — the bit-exactness oracle
        expected = prefill.submit(PROMPT, SP).result(timeout=120)
        assert result.text == expected.text
        assert result.tokens == expected.tokens
    finally:
        teardown(router, prefill, decode)


def test_handoff_wire_failure_falls_back_byte_identical():
    """``fleet.handoff_error`` drops the entry between export and inject:
    the decode replica runs a full local prefill instead, output
    unchanged — the handoff is an optimization, never a dependency."""
    router, prefill, decode = make_disagg_pool()
    baseline = make_engine()
    try:
        FAULTS.arm("fleet.handoff_error", times=1)
        result = router.submit(PROMPT, SP, affinity_key="p").result(timeout=180)
        assert router.handoffs == 0 and router.handoff_errors == 1
        assert decode.kv_injects == 0
        expected = baseline.submit(PROMPT, SP).result(timeout=120)
        assert result.text == expected.text
    finally:
        teardown(router, prefill, decode, baseline)


def test_short_prompt_skips_handoff():
    """Below ``handoff_min_tokens`` the router doesn't bother with the
    prefill leg at all — straight local dispatch on the decode replica."""
    router, prefill, decode = make_disagg_pool()
    try:
        result = router.submit("hi", SP, affinity_key="p").result(timeout=120)
        assert router.handoffs == 0 and router.handoff_errors == 0
        # the prefill replica never saw the request: no tokens generated
        assert prefill.stats()["tokens_generated"] == 0
        assert result.finish_reason in ("stop", "length")
    finally:
        teardown(router, prefill, decode)


def test_handoff_disabled_by_default():
    """``handoff_min_tokens=0`` (the default) never routes a prefill leg
    even with a prefill replica registered."""
    router = FleetRouter(store=Store(), heartbeat_interval=60.0)
    prefill = make_engine()
    decode = make_engine()
    router.add_replica("pf", prefill, role="prefill")
    router.add_replica("dc", decode, role="decode")
    try:
        router.submit(PROMPT, SP, affinity_key="p").result(timeout=120)
        assert router.handoffs == 0
        assert router.stats()["handoff"]["enabled"] is False
    finally:
        teardown(router, prefill, decode)
