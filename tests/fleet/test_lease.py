"""kernel/lease.py fleet seams: the read-only ``holder()`` view and the
``LeaseHeartbeat`` renewer (add/beat/deposition/on_lost/release)."""

from __future__ import annotations

import time

from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.kernel.lease import (
    LeaseHeartbeat,
    holder,
    release,
    try_acquire_epoch,
)


def test_holder_absent_live_expired_released():
    store = Store()
    assert holder(store, "l") is None
    now = time.time()
    assert try_acquire_epoch(store, "l", "me", ttl=10.0, now=now) == 1
    assert holder(store, "l", now=now) == "me"
    # expired: the holder is stale, not live
    assert holder(store, "l", now=now + 11.0) is None
    # released: the Lease object survives (epoch continuity) but reads empty
    release(store, "l", "me")
    assert holder(store, "l") is None
    # adoption after release bumps the epoch — fencing is monotonic
    assert try_acquire_epoch(store, "l", "other", ttl=10.0) == 2


def test_heartbeat_add_renews_and_tracks_epochs():
    store = Store()
    hb = LeaseHeartbeat(store, interval=60.0, ttl=10.0)
    assert hb.add("fleet-replica-r0", "pool-a") == 1
    assert hb.epochs["fleet-replica-r0"] == 1
    assert holder(store, "fleet-replica-r0") == "pool-a"
    hb.beat()  # renewal keeps the epoch stable (no takeover)
    assert hb.epochs["fleet-replica-r0"] == 1
    # a second pool cannot steal a live lease, and is not tracked
    hb2 = LeaseHeartbeat(store, interval=60.0, ttl=10.0)
    assert hb2.add("fleet-replica-r0", "pool-b") is None
    assert "fleet-replica-r0" not in hb2.epochs


def test_heartbeat_deposed_lease_reports_on_lost():
    store = Store()
    lost = []
    hb = LeaseHeartbeat(store, interval=60.0, ttl=0.05, on_lost=lost.append)
    assert hb.add("fleet-replica-r0", "pool-a") == 1
    time.sleep(0.1)  # let the lease expire un-renewed
    # another identity adopts the expired lease (epoch bump)...
    assert try_acquire_epoch(store, "fleet-replica-r0", "pool-b", ttl=30.0) == 2
    # ...so the original owner's next beat discovers the deposition
    hb.beat()
    assert lost == ["fleet-replica-r0"]
    assert "fleet-replica-r0" not in hb.epochs
    # deposition is terminal for this tracking entry: no further churn
    hb.beat()
    assert lost == ["fleet-replica-r0"]


def test_heartbeat_remove_releases_for_instant_adoption():
    store = Store()
    hb = LeaseHeartbeat(store, interval=60.0, ttl=30.0)
    hb.add("fleet-replica-r0", "pool-a")
    hb.remove("fleet-replica-r0", release_lease=True)
    # no TTL wait: a survivor adopts immediately, fencing epoch bumped
    assert try_acquire_epoch(store, "fleet-replica-r0", "pool-a/r1",
                             ttl=30.0) == 2


def test_heartbeat_thread_keeps_lease_live():
    store = Store()
    hb = LeaseHeartbeat(store, interval=0.05, ttl=0.3)
    hb.add("fleet-replica-r0", "pool-a")
    hb.start()
    try:
        time.sleep(0.6)  # > 2x TTL: only renewals keep it live
        assert holder(store, "fleet-replica-r0") == "pool-a"
    finally:
        hb.stop()
