"""Hedged re-dispatch (fleet/router.py watchdog): a request stuck
pre-first-token on a gray replica races a second attempt on a healthy
one — first delivery wins, streams stay exactly-once and byte-identical.
Also pins the all-replicas-down shed contract and lease deposition
during an in-flight hedge."""

from __future__ import annotations

import dataclasses
import time

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import (
    PRESETS,
    Engine,
    EngineOverloadedError,
    SamplingParams,
)
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.fleet.health import HealthPolicy
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256,
                          n_kv_heads=2)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def _wait_for(predicate, timeout_s=30.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout="paged",
        page_size=8, **kw,
    )
    eng.start()
    return eng


def make_hedging_pool(n=2, **router_kw):
    """A pool tuned so a throttled replica degrades within a few watchdog
    ticks and a stuck request hedges shortly after."""
    router_kw.setdefault("hedge_after_s", 0.3)
    router_kw.setdefault("watchdog_interval_s", 0.1)
    router_kw.setdefault("health_policy", HealthPolicy(degrade_after=1))
    router_kw.setdefault("heartbeat_interval", 60.0)
    router = FleetRouter(store=Store(), **router_kw)
    engines = [make_engine(stall_mult=2.0, stall_min_s=0.02)
               for _ in range(n)]
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng)
    return router, engines


def teardown_pool(router, engines, extra=()):
    router.stop()
    for eng in list(engines) + list(extra):
        try:
            eng.stop()
        except Exception:
            pass


def warm_floor(router):
    """One unthrottled request per replica so every engine's cadence
    floor (the stall baseline) reflects honest post-compile cycles."""
    sp = SamplingParams(temperature=0.0, max_tokens=16)
    for replica in router.pool.replicas():
        replica.engine.submit("warm the cadence floor", sp).result(timeout=120)


def saturate_then_throttle(router, target, delay_s=0.3, times=40):
    """Pin a request pre-first-token on ``target``: fill every slot with
    decoy work FIRST (so the next submit parks in the waiting queue,
    zero tokens delivered), then throttle the cycles. Stalls record at
    the END of throttled cycles, so degradation can only outrun a
    request's first token when that request can't even prefill."""
    decoy_sp = SamplingParams(temperature=0.0, max_tokens=48)
    decoys = [
        router.pool.get(target).engine.submit(f"decoy {i}", decoy_sp)
        for i in range(4)  # == max_slots
    ]
    FAULTS.arm("engine.slow_cycle", times=times, delay_s=delay_s,
               replica=target)
    return decoys


def test_hedge_rescues_stuck_request_byte_identical():
    """The acceptance guarantee: a request stuck pre-first-token on a
    throttled replica is hedge re-dispatched onto the healthy one; the
    caller sees one contiguous stream, byte-identical to a clean single
    engine; the loser attempt is cancelled (no double delivery)."""
    router, engines = make_hedging_pool(2)
    baseline = make_engine()
    try:
        warm_floor(router)
        prompt = "tell me about gray failures"
        sp = SamplingParams(temperature=0.0, max_tokens=24)
        router.submit("warm the persona", SamplingParams(temperature=0.0,
                      max_tokens=2), affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        decoys = saturate_then_throttle(router, target)
        streamed = []
        fut = router.submit(prompt, sp, affinity_key="p",
                            on_tokens=streamed.extend)
        result = fut.result(timeout=180)
        expected = baseline.submit(prompt, sp).result(timeout=120)
        assert result.text == expected.text
        assert result.tokens == expected.tokens
        # exactly-once: the stream IS the result, no replayed prefix
        assert streamed == list(result.tokens)
        assert router.hedges == 1
        stats = router.stats()
        assert stats["health"]["hedges"] == 1
        # the winner came from the healthy replica, not the gray one
        assert router.pool.get(target).alive  # gray, not dead
        for d in decoys:
            d.result(timeout=180)
    finally:
        teardown_pool(router, engines, extra=[baseline])


def test_all_replicas_dead_sheds_with_pool_retry_after():
    """Satellite pin: when every replica is dead, submit() must shed
    (503-style EngineOverloadedError with a Retry-After) instead of
    raising out of an empty candidate list."""
    router, engines = make_hedging_pool(2, hedge_after_s=0.0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        # kill both replicas through the normal crash path, one at a time
        for victim in ("r0", "r1"):
            FAULTS.arm("fleet.replica_crash", times=1, replica=victim)
            try:
                router.submit(f"crash {victim}", sp).result(timeout=120)
            except RuntimeError:
                pass  # the last crash has no survivor to fail over to
        assert not router.pool.alive()
        # a FRESH submission into the dead pool: shed, never a crash
        with pytest.raises(EngineOverloadedError) as exc_info:
            router.submit("anyone home?", sp).result(timeout=30)
        assert "no live replicas" in str(exc_info.value)
        assert exc_info.value.retry_after_s > 0
    finally:
        teardown_pool(router, engines)


def test_lease_deposition_during_inflight_hedge_no_double_delivery():
    """Satellite: the gray replica CRASHES (lease deposed, survivor
    adopts) with a hedged request AND a mid-stream request in flight.
    The mid-stream sentinel never hedges (tokens already delivered) so
    it is the router's observer of the death: its attempt fails, the
    survivor adopts the lease, and the failover resumes its stream with
    NO replayed prefix — both requests byte-identical, exactly-once."""
    # hedge holdoff past the throttled prefill (~0.3 s) so the sentinel
    # delivers its first token before it could ever look stuck
    router, engines = make_hedging_pool(2, hedge_after_s=0.5)
    baseline = make_engine()
    try:
        warm_floor(router)
        router.submit("warm the persona", SamplingParams(temperature=0.0,
                      max_tokens=2), affinity_key="p").result(timeout=120)
        target = router._affinity["p"]
        FAULTS.arm("engine.slow_cycle", times=40, delay_s=0.3,
                   replica=target)
        # the sentinel: homed on target, streams slowly under the
        # throttle — its delivered tokens exempt it from hedging, so its
        # attempt stays live on the gray replica until the crash
        sent_sp = SamplingParams(temperature=0.0, max_tokens=40)
        sent_streamed = []
        sentinel = router.submit("survive the deposition", sent_sp,
                                 affinity_key="p",
                                 on_tokens=sent_streamed.extend)
        # fill the remaining slots so the hedged request stays queued;
        # all submits land inside the first throttled cycle, before the
        # watchdog can degrade the target and shed the "p" home
        decoy_sp = SamplingParams(temperature=0.0, max_tokens=48)
        router.pool.get(target).engine.submit("decoy a", decoy_sp)
        router.pool.get(target).engine.submit("decoy b", decoy_sp)
        router.pool.get(target).engine.submit("decoy c", decoy_sp)
        prompt = "tell me about lease fencing"
        sp = SamplingParams(temperature=0.0, max_tokens=24)
        streamed = []
        fut = router.submit(prompt, sp, affinity_key="p",
                            on_tokens=streamed.extend)
        result = fut.result(timeout=180)  # hedge rescues it
        assert _wait_for(lambda: len(sent_streamed) > 0), \
            "sentinel never started streaming"
        # now depose the gray replica mid-sentinel-stream: the crash pops
        # on its next throttled cycle, the sentinel's attempt fails, and
        # the survivor adopts the lease + resumes the stream
        FAULTS.arm("fleet.replica_crash", times=1, replica=target)
        sent_result = sentinel.result(timeout=180)
        expected = baseline.submit(prompt, sp).result(timeout=120)
        sent_expected = baseline.submit("survive the deposition",
                                        sent_sp).result(timeout=120)
        assert result.text == expected.text
        assert streamed == list(result.tokens)
        assert sent_result.text == sent_expected.text
        assert sent_result.tokens == sent_expected.tokens
        # exactly-once across the failover: the resumed stream continues
        # where the dead replica left off, no replayed prefix
        assert sent_streamed == list(sent_result.tokens)
        assert router.hedges >= 1
        dead = router.pool.get(target)
        survivor = [r for r in router.pool.replicas()
                    if r.id != target][0]
        assert _wait_for(lambda: not dead.alive), "crash never landed"
        assert router.pool.lease_holder(dead).endswith("/" + survivor.id)
    finally:
        teardown_pool(router, engines, extra=[baseline])
