"""OpenAI-compatible + Anthropic wire-format tests against a local fake
server (the reference's getting_started suite fakes OpenAI with httptest)."""

import json

import pytest
from aiohttp import web

from agentcontrolplane_tpu.api.resources import BaseConfig, Message, MessageToolCall, ToolCallFunction
from agentcontrolplane_tpu.llmclient import (
    AnthropicClient,
    LLMRequestError,
    OpenAICompatibleClient,
    Tool,
    ToolFunction,
    merge_choices,
)


class FakeProvider:
    def __init__(self, responder):
        self.responder = responder
        self.requests = []
        self.app = web.Application()
        self.app.router.add_post("/chat/completions", self.handle)
        self.app.router.add_post("/v1/messages", self.handle)
        self.runner = None
        self.port = None

    async def handle(self, request):
        body = await request.json()
        self.requests.append((request.path, dict(request.headers), body))
        result = self.responder(body)
        if isinstance(result, tuple):
            status, payload = result
            return web.json_response(payload, status=status)
        return web.json_response(result)

    async def __aenter__(self):
        self.runner = web.AppRunner(self.app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def __aexit__(self, *exc):
        await self.runner.cleanup()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"


async def test_openai_roundtrip_with_tools():
    def responder(body):
        assert body["model"] == "gpt-4o"
        assert body["messages"][0] == {"role": "system", "content": "sys"}
        assert body["tools"][0]["function"]["name"] == "web__fetch"
        return {
            "choices": [
                {
                    "message": {
                        "role": "assistant",
                        "content": None,
                        "tool_calls": [
                            {
                                "id": "call_9",
                                "type": "function",
                                "function": {"name": "web__fetch", "arguments": '{"url": "x"}'},
                            }
                        ],
                    }
                }
            ]
        }

    async with FakeProvider(responder) as fake:
        client = OpenAICompatibleClient(
            "sk-test", BaseConfig(model="gpt-4o", base_url=fake.url, temperature=0.5)
        )
        msg = await client.send_request(
            [Message(role="system", content="sys"), Message(role="user", content="u")],
            [Tool(function=ToolFunction(name="web__fetch", description="d"))],
        )
        await client.close()
    assert msg.tool_calls[0].function.name == "web__fetch"
    assert msg.tool_calls[0].id == "call_9"
    assert msg.content == ""
    # auth header + sampling params went over the wire
    path, headers, body = fake.requests[0]
    assert headers["Authorization"] == "Bearer sk-test"
    assert body["temperature"] == 0.5


async def test_openai_tool_result_message_encoding():
    def responder(body):
        tool_msg = body["messages"][-1]
        assert tool_msg == {"role": "tool", "content": "result!", "tool_call_id": "call_1"}
        assistant = body["messages"][-2]
        assert assistant["tool_calls"][0]["id"] == "call_1"
        assert assistant["content"] is None
        return {"choices": [{"message": {"role": "assistant", "content": "done"}}]}

    async with FakeProvider(responder) as fake:
        client = OpenAICompatibleClient("k", BaseConfig(model="m", base_url=fake.url))
        msg = await client.send_request(
            [
                Message(role="user", content="u"),
                Message(
                    role="assistant",
                    content="",
                    tool_calls=[
                        MessageToolCall(
                            id="call_1",
                            function=ToolCallFunction(name="a__b", arguments="{}"),
                        )
                    ],
                ),
                Message(role="tool", content="result!", tool_call_id="call_1"),
            ],
            [],
        )
        await client.close()
    assert msg.content == "done"


async def test_openai_4xx_maps_to_terminal_error():
    async with FakeProvider(lambda b: (401, {"error": {"message": "bad key"}})) as fake:
        client = OpenAICompatibleClient("k", BaseConfig(model="m", base_url=fake.url))
        with pytest.raises(LLMRequestError) as exc:
            await client.send_request([Message(role="user", content="u")], [])
        await client.close()
    assert exc.value.status_code == 401
    assert exc.value.terminal
    assert "bad key" in str(exc.value)


async def test_openai_429_is_retryable():
    async with FakeProvider(lambda b: (429, {"error": {"message": "slow down"}})) as fake:
        client = OpenAICompatibleClient("k", BaseConfig(model="m", base_url=fake.url))
        with pytest.raises(LLMRequestError) as exc:
            await client.send_request([Message(role="user", content="u")], [])
        await client.close()
    assert not exc.value.terminal


async def test_anthropic_roundtrip_tool_use():
    def responder(body):
        assert body["system"] == "sys"
        assert body["messages"][0] == {"role": "user", "content": "u"}
        assert body["tools"][0]["input_schema"]["type"] == "object"
        return {
            "content": [
                {"type": "text", "text": "let me check"},
                {"type": "tool_use", "id": "tu_1", "name": "web__fetch", "input": {"url": "x"}},
            ]
        }

    async with FakeProvider(responder) as fake:
        client = AnthropicClient("ak", BaseConfig(model="claude", base_url=fake.url))
        msg = await client.send_request(
            [Message(role="system", content="sys"), Message(role="user", content="u")],
            [Tool(function=ToolFunction(name="web__fetch", description="d"))],
        )
        await client.close()
    # tool calls beat content
    assert msg.content == ""
    assert msg.tool_calls[0].function.name == "web__fetch"
    assert json.loads(msg.tool_calls[0].function.arguments) == {"url": "x"}
    _, headers, _ = fake.requests[0]
    assert headers["x-api-key"] == "ak"


async def test_anthropic_tool_result_encoding():
    def responder(body):
        result_msg = body["messages"][-1]
        assert result_msg["content"][0]["type"] == "tool_result"
        assert result_msg["content"][0]["tool_use_id"] == "call_1"
        return {"content": [{"type": "text", "text": "final"}]}

    async with FakeProvider(responder) as fake:
        client = AnthropicClient("ak", BaseConfig(model="c", base_url=fake.url))
        msg = await client.send_request(
            [
                Message(role="user", content="u"),
                Message(
                    role="assistant",
                    content="",
                    tool_calls=[
                        MessageToolCall(
                            id="call_1",
                            function=ToolCallFunction(name="a__b", arguments='{"k":1}'),
                        )
                    ],
                ),
                Message(role="tool", content="res", tool_call_id="call_1"),
            ],
            [],
        )
        await client.close()
    assert msg.content == "final"


def test_merge_choices_rules():
    # tool calls across choices collected; content cleared
    merged = merge_choices(
        [
            Message(role="assistant", content="text answer"),
            Message(
                role="assistant",
                content="",
                tool_calls=[
                    MessageToolCall(id="1", function=ToolCallFunction(name="t__a"))
                ],
            ),
        ]
    )
    assert merged.content == "" and len(merged.tool_calls) == 1
    # no tool calls -> first non-empty content
    merged = merge_choices(
        [Message(role="assistant", content=""), Message(role="assistant", content="second")]
    )
    assert merged.content == "second"
    # empty response
    assert merge_choices([]).content == ""
