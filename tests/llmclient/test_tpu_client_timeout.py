"""Spec-configurable engine request timeout (VERDICT r2 #9).

The reference bounds every LLM call at 30 s (LLMRequestTimeout,
acp/internal/controller/task/task_controller.go:25) so a wedged provider
can't hold the per-task lease. provider: tpu must honor the same contract:
LLM.spec.tpu.requestTimeoutSeconds flows to TPUEngineClient, a timed-out
generation raises a retryable 5xx, and the request's slot is cancelled so
the engine stops decoding for a dead caller.
"""

from __future__ import annotations

import asyncio
from concurrent.futures import Future

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LLM,
    BaseConfig,
    LLMSpec,
    Message,
    TPUProviderConfig,
)
from agentcontrolplane_tpu.engine.client import TPUEngineClient
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.llmclient.base import LLMRequestError
from agentcontrolplane_tpu.llmclient.factory import DefaultLLMClientFactory


class _StuckEngine:
    """Engine stub whose generations never finish (wedged device)."""

    tokenizer = ByteTokenizer()

    def __init__(self):
        self.cancelled: list[Future] = []

    def ensure_running(self) -> bool:
        return True

    def submit(self, prompt, sampling, timeout_s=None, **kw) -> Future:
        return Future()  # never resolves

    def cancel(self, future: Future) -> None:
        self.cancelled.append(future)


def test_timed_out_generation_raises_5xx_and_frees_the_slot():
    engine = _StuckEngine()
    client = TPUEngineClient(engine, BaseConfig(), request_timeout_s=0.1)

    async def run():
        with pytest.raises(LLMRequestError) as ei:
            await client.send_request([Message(role="user", content="hi")], [])
        return ei.value

    err = asyncio.run(run())
    assert err.status_code == 504  # 5xx -> the task reconciler retries
    assert len(engine.cancelled) == 1  # slot freed; no decode for a dead caller


def test_request_timeout_flows_from_llm_spec():
    factory = DefaultLLMClientFactory(engine=_StuckEngine())
    llm = LLM(
        metadata=ObjectMeta(name="l"),
        spec=LLMSpec(
            provider="tpu",
            parameters=BaseConfig(),
            tpu=TPUProviderConfig(preset="tiny", request_timeout_seconds=7.5),
        ),
    )
    client = asyncio.run(factory.create_client(llm, ""))
    assert isinstance(client, TPUEngineClient)
    assert client.request_timeout_s == 7.5


def test_request_timeout_default_matches_reference():
    assert TPUProviderConfig().request_timeout_seconds == 30.0
