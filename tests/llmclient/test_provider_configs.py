"""Typed per-provider LLM configs + native Google service-account auth.

Reference surface: the per-provider config blocks
(acp/api/v1alpha1/llm_types.go:73-141) and the vertex credentials-JSON flow
(acp/internal/llmclient/langchaingo_client.go:65-70). The token exchange is
driven against a FAKED token endpoint — no Google, no network egress.
"""

from __future__ import annotations

import base64
import json

import pytest
from aiohttp import web

from agentcontrolplane_tpu.api.meta import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LLM,
    AnthropicProviderConfig,
    BaseConfig,
    LLMSpec,
    Message,
    MistralProviderConfig,
    OpenAIProviderConfig,
    VertexProviderConfig,
)
from agentcontrolplane_tpu.kernel.errors import Invalid
from agentcontrolplane_tpu.llmclient import DefaultLLMClientFactory
from agentcontrolplane_tpu.llmclient.googleauth import (
    ServiceAccountTokenSource,
    looks_like_service_account,
)

from .test_providers import FakeProvider

CHAT_RESPONSE = {
    "choices": [{"message": {"role": "assistant", "content": "ok"}}]
}
ANTHROPIC_RESPONSE = {
    "content": [{"type": "text", "text": "ok"}],
    "stop_reason": "end_turn",
}


def make_sa_credential(token_uri: str) -> str:
    """A real RSA keypair in a service_account JSON document."""
    pytest.importorskip("cryptography")  # needed only to mint the test key
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()
    return json.dumps({
        "type": "service_account",
        "client_email": "robot@proj.iam.gserviceaccount.com",
        "private_key": pem,
        "token_uri": token_uri,
    })


class FakeTokenEndpoint:
    """Stands in for oauth2.googleapis.com/token."""

    def __init__(self):
        self.assertions: list[dict] = []
        self.minted = 0
        self.app = web.Application()
        self.app.router.add_post("/token", self.handle)
        self.runner = None
        self.url = None

    async def handle(self, request):
        form = await request.post()
        assert form["grant_type"] == "urn:ietf:params:oauth:grant-type:jwt-bearer"
        header, claims, _sig = form["assertion"].split(".")
        pad = lambda s: s + "=" * (-len(s) % 4)
        self.assertions.append({
            "header": json.loads(base64.urlsafe_b64decode(pad(header))),
            "claims": json.loads(base64.urlsafe_b64decode(pad(claims))),
        })
        self.minted += 1
        return web.json_response(
            {"access_token": f"tok-{self.minted}", "expires_in": 3600}
        )

    async def __aenter__(self):
        self.runner = web.AppRunner(self.app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://127.0.0.1:{port}/token"
        return self

    async def __aexit__(self, *exc):
        await self.runner.cleanup()


def _llm(provider: str, **spec_kwargs) -> LLM:
    return LLM(
        metadata=ObjectMeta(name="llm"),
        spec=LLMSpec(provider=provider, **spec_kwargs),
    )


# -- service-account token source -------------------------------------------


def test_looks_like_service_account():
    assert looks_like_service_account('{"type": "service_account"}')
    assert not looks_like_service_account("sk-ant-12345")
    assert not looks_like_service_account('{"type": "authorized_user"}')
    assert not looks_like_service_account("{not json")


def test_sa_credential_validation():
    with pytest.raises(Invalid, match="missing fields"):
        ServiceAccountTokenSource('{"type": "service_account"}')
    with pytest.raises(Invalid, match="not JSON"):
        ServiceAccountTokenSource("nope")


async def test_token_mint_claims_and_caching():
    import httpx

    async with FakeTokenEndpoint() as fake:
        source = ServiceAccountTokenSource(make_sa_credential(fake.url))
        async with httpx.AsyncClient() as http:
            tok = await source.token(http)
            assert tok == "tok-1"
            assert fake.assertions[0]["header"]["alg"] == "RS256"
            claims = fake.assertions[0]["claims"]
            assert claims["iss"] == "robot@proj.iam.gserviceaccount.com"
            assert claims["aud"] == fake.url
            assert claims["scope"].endswith("cloud-platform")
            assert claims["exp"] > claims["iat"]

            # cached until expiry: no second mint
            assert await source.token(http) == "tok-1"
            assert fake.minted == 1

            # invalidate (e.g. server-side 401) -> fresh token
            source.invalidate()
            assert await source.token(http) == "tok-2"


# -- factory wiring ----------------------------------------------------------


async def test_vertex_service_account_flow_end_to_end():
    """LLM(provider=vertex) with an SA-JSON credential: the factory builds a
    client whose requests carry a token minted from the faked endpoint."""
    factory = DefaultLLMClientFactory()
    try:
        async with FakeTokenEndpoint() as fake, FakeProvider(
            lambda body: CHAT_RESPONSE
        ) as provider:
            llm = _llm(
                "vertex",
                parameters=BaseConfig(model="gemini-pro", base_url=provider.url),
                vertex=VertexProviderConfig(
                    cloud_project="proj", cloud_location="us-central1"
                ),
            )
            client = await factory.create_client(llm, make_sa_credential(fake.url))
            msg = await client.send_request(
                [Message(role="user", content="hi")], []
            )
            assert msg.content == "ok"
            _, headers, _ = provider.requests[0]
            assert headers["Authorization"] == "Bearer tok-1"
            assert fake.minted == 1
    finally:
        await factory.aclose()


async def test_vertex_base_url_derived_from_typed_config():
    factory = DefaultLLMClientFactory()
    try:
        llm = _llm(
            "vertex",
            vertex=VertexProviderConfig(
                cloud_project="proj", cloud_location="europe-west4"
            ),
        )
        client = await factory.create_client(llm, "ya29.raw-access-token")
        assert str(client._http.base_url).startswith(
            "https://europe-west4-aiplatform.googleapis.com/v1/projects/proj"
        )
    finally:
        await factory.aclose()


async def test_vertex_requires_typed_config_or_base_url():
    factory = DefaultLLMClientFactory()
    with pytest.raises(Invalid, match="cloudProject"):
        await factory.create_client(_llm("vertex"), "key")


async def test_openai_organization_header(monkeypatch):
    factory = DefaultLLMClientFactory()
    try:
        async with FakeProvider(lambda body: CHAT_RESPONSE) as provider:
            llm = _llm(
                "openai",
                parameters=BaseConfig(model="gpt-4o", base_url=provider.url),
                openai=OpenAIProviderConfig(organization="org-abc"),
            )
            client = await factory.create_client(llm, "sk-x")
            await client.send_request([], [])
            _, headers, _ = provider.requests[0]
            assert headers["OpenAI-Organization"] == "org-abc"
            assert headers["Authorization"] == "Bearer sk-x"
    finally:
        await factory.aclose()


async def test_azure_api_type_key_header_and_version():
    factory = DefaultLLMClientFactory()
    try:
        async with FakeProvider(lambda body: CHAT_RESPONSE) as provider:
            llm = _llm(
                "openai",
                parameters=BaseConfig(model="gpt-4o", base_url=provider.url),
                openai=OpenAIProviderConfig(
                    api_type="AZURE", api_version="2023-05-15"
                ),
            )
            client = await factory.create_client(llm, "azure-key")
            await client.send_request([], [])
            path, headers, _ = provider.requests[0]
            assert headers["api-key"] == "azure-key"
            assert "Authorization" not in headers
    finally:
        await factory.aclose()


def test_azure_requires_api_version():
    with pytest.raises(ValueError, match="apiVersion"):
        OpenAIProviderConfig(api_type="AZURE")


async def test_mistral_random_seed_and_timeout():
    factory = DefaultLLMClientFactory()
    try:
        async with FakeProvider(lambda body: CHAT_RESPONSE) as provider:
            llm = _llm(
                "mistral",
                parameters=BaseConfig(model="mistral-large", base_url=provider.url),
                mistral=MistralProviderConfig(random_seed=42, timeout=7),
            )
            client = await factory.create_client(llm, "key")
            await client.send_request([], [])
            _, _, body = provider.requests[0]
            assert body["random_seed"] == 42
            assert client._http.timeout.read == 7.0
    finally:
        await factory.aclose()


async def test_anthropic_beta_header():
    factory = DefaultLLMClientFactory()
    try:
        async with FakeProvider(lambda body: ANTHROPIC_RESPONSE) as provider:
            llm = _llm(
                "anthropic",
                parameters=BaseConfig(model="claude-3-5-sonnet", base_url=provider.url),
                anthropic=AnthropicProviderConfig(
                    anthropic_beta_header="max-tokens-3-5-sonnet-2024-07-15"
                ),
            )
            client = await factory.create_client(llm, "sk-ant")
            await client.send_request([], [])
            _, headers, _ = provider.requests[0]
            assert headers["anthropic-beta"] == "max-tokens-3-5-sonnet-2024-07-15"
    finally:
        await factory.aclose()
