"""The bench parent/child watchdog protocol (bench.py).

Rounds 1 and 2 both shipped BENCH_rNN.json = 0.0 because the bench's main
process initialized PJRT itself and hung on a wedged tunnel. The round-3
contract: the parent NEVER touches PJRT, children report MARK/RESULT lines,
and the parent kills + retries a child that misses a mark deadline. These
tests drive that protocol against stub children (no JAX involved).
"""

from __future__ import annotations

import os
import sys
import textwrap
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench


@pytest.fixture(autouse=True)
def _lkg_redirect(tmp_path, monkeypatch):
    """EVERY test in this file writes last-known-good (if at all) to a tmp
    path. Round-4 postmortem: a test drove the real _parent() with a stub
    child + faked TPU probe and silently rewrote the REAL
    /tmp/tpu_runs/last_known_good.json with a fabricated number that the
    driver then embedded in the judged BENCH_r04.json. Belt (this fixture)
    and braces (_lkg_refusal rejects pytest/stub provenance)."""
    monkeypatch.setenv("ACP_BENCH_LKG_PATH", str(tmp_path / "lkg.json"))


@pytest.fixture
def stub_child(tmp_path, monkeypatch):
    """Point bench._THIS at a stub script; returns a setter for its body."""

    def make(body: str) -> str:
        path = tmp_path / "stub_child.py"
        path.write_text(
            "import sys, time, json\n" + textwrap.dedent(body)
        )
        monkeypatch.setattr(bench, "_THIS", str(path))
        return str(path)

    return make


def test_phase_run_collects_marks_and_results(stub_child):
    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        print("diagnostic noise", flush=True)
        print("MARK engine_built", flush=True)
        print('RESULT headline {"tok_s_per_chip": 123.4, "note": "n"}', flush=True)
        """
    )
    run = bench._PhaseRun(["--phase", "main"])
    status = run.run_schedule(
        [("attach_ok", 10), ("engine_built", 10), ("RESULT headline", 10)],
        hard_deadline=time.monotonic() + 30,
    )
    assert status == "ok"
    assert run.results["headline"]["tok_s_per_chip"] == 123.4


def test_phase_run_kills_child_that_misses_a_mark(stub_child):
    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        time.sleep(600)  # simulates a hung PJRT attach after the first mark
        """
    )
    run = bench._PhaseRun(["--phase", "main"])
    t0 = time.monotonic()
    status = run.run_schedule(
        [("attach_ok", 10), ("engine_built", 2), ("RESULT headline", 10)],
        hard_deadline=time.monotonic() + 60,
    )
    assert status == "engine_built"
    assert time.monotonic() - t0 < 30  # did not wait out the sleep
    assert run.proc.poll() is not None  # child is dead


def test_phase_run_keeps_partial_results_from_killed_child(stub_child):
    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        print("MARK engine_built", flush=True)
        print("MARK warm_done", flush=True)
        print('RESULT headline {"tok_s_per_chip": 999.0, "note": "n"}', flush=True)
        time.sleep(600)  # hangs during the TTFT leg
        """
    )
    run = bench._PhaseRun(["--phase", "main"])
    status = run.run_schedule(
        [("attach_ok", 10), ("engine_built", 10), ("warm_done", 10),
         ("RESULT headline", 10), ("RESULT ttft", 2)],
        hard_deadline=time.monotonic() + 60,
    )
    assert status == "RESULT ttft"
    assert run.results["headline"]["tok_s_per_chip"] == 999.0  # partial kept


def test_phase_run_child_exit_without_mark_is_a_miss(stub_child):
    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        sys.exit(3)  # crashed before building the engine
        """
    )
    run = bench._PhaseRun(["--phase", "main"])
    status = run.run_schedule(
        [("attach_ok", 10), ("engine_built", 5)],
        hard_deadline=time.monotonic() + 30,
    )
    assert status == "engine_built"


def test_unparseable_result_line_does_not_crash_reader(stub_child):
    stub_child(
        """
        print("RESULT headline {not json", flush=True)
        print('RESULT headline {"tok_s_per_chip": 1.0}', flush=True)
        """
    )
    run = bench._PhaseRun(["--phase", "main"])
    status = run.run_schedule(
        [("RESULT headline", 10)], hard_deadline=time.monotonic() + 30
    )
    assert status == "ok"
    assert run.results["headline"] == {"tok_s_per_chip": 1.0}


def test_parent_never_imports_engine_or_inits_pjrt():
    """Static contract: the parent path must not call jax.devices() or
    import the engine — only children may. Guards against regressing to the
    r01/r02 architecture."""
    import ast
    import inspect

    parent_src = textwrap.dedent(inspect.getsource(bench._parent)) + "\n" + textwrap.dedent(
        inspect.getsource(bench._parent_run)
    )
    tree = ast.parse(parent_src)
    calls = [
        n for n in ast.walk(tree)
        if isinstance(n, ast.Attribute) and n.attr in ("devices", "local_devices")
    ]
    assert not calls, "parent must never call jax.devices()"
    imports = [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.Import, ast.ImportFrom))
        and "agentcontrolplane_tpu" in ast.dump(n)
    ]
    assert not imports, "parent must not import the engine package"


def test_probe_rejects_cpu_fallback(monkeypatch):
    """r3 root cause (a): when the axon plugin is down JAX silently reports
    one CPU device. The probe must read that as tunnel-down, not success."""
    import subprocess as sp

    def fake_run(argv, **kw):
        return sp.CompletedProcess(
            argv, 0, stdout='{"backend": "cpu", "n": 1, "device_kind": "cpu"}\n',
            stderr="",
        )

    monkeypatch.delenv("ACP_BENCH_ALLOW_CPU", raising=False)
    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._probe_once(5.0) is None
    monkeypatch.setenv("ACP_BENCH_ALLOW_CPU", "1")
    assert bench._probe_once(5.0)["backend"] == "cpu"


def test_probe_accepts_tpu_backend(monkeypatch):
    import subprocess as sp

    def fake_run(argv, **kw):
        return sp.CompletedProcess(
            argv, 0,
            stdout='{"backend": "tpu", "n": 1, "device_kind": "TPU v5e"}\n',
            stderr="",
        )

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    info = bench._probe_once(5.0)
    assert info == {"backend": "tpu", "n": 1, "device_kind": "TPU v5e"}


def test_parent_flushes_headline_incrementally(stub_child, monkeypatch, capsys):
    """r3 root cause (b): the driver SIGKILLed before the final emit. The
    parent must re-print the JSON line the moment the headline result lands,
    so the freshest flushed line already carries the number."""
    import json

    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        print("MARK engine_built", flush=True)
        print("MARK warm_done", flush=True)
        print('RESULT headline {"tok_s_per_chip": 777.0, "note": "stub"}', flush=True)
        """
    )
    monkeypatch.setattr(bench, "_cpu_forced_inline", lambda: False)
    monkeypatch.setattr(
        bench, "_probe_until",
        lambda *a, **k: {"backend": "tpu", "n": 1, "device_kind": "TPU v5e"},
    )
    monkeypatch.setenv("ACP_BENCH_TTFT", "0")
    monkeypatch.setenv("ACP_BENCH_AB", "0")
    monkeypatch.setenv("ACP_BENCH_TOTAL_BUDGET_S", "600")
    bench._parent()
    lines = [
        json.loads(ln)
        for ln in capsys.readouterr().out.strip().splitlines()
        if ln.startswith("{")
    ]
    # ≥3 flushes: platform probe, headline capture, final
    assert len(lines) >= 3
    assert lines[0]["platform"]["backend"] == "tpu"
    assert lines[0]["value"] == 0.0
    # the headline-capture flush (not just the final one) carries the number
    assert lines[1]["value"] == 777.0
    assert lines[-1]["value"] == 777.0
    assert lines[-1]["vs_baseline"] == 0.777


def test_stub_run_is_never_persisted_as_last_known_good(
    stub_child, monkeypatch, capsys, tmp_path
):
    """The round-4 leak, replayed: real _parent(), stub child reporting a
    fabricated number, probe faked as a TPU — and the LKG file must NOT be
    written. Two independent guards fire here (stub note + pytest env);
    either alone must hold."""
    lkg = tmp_path / "lkg_guard.json"
    monkeypatch.setenv("ACP_BENCH_LKG_PATH", str(lkg))
    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        print("MARK engine_built", flush=True)
        print("MARK warm_done", flush=True)
        print('RESULT headline {"tok_s_per_chip": 777.0, "note": "stub"}', flush=True)
        """
    )
    monkeypatch.setattr(bench, "_cpu_forced_inline", lambda: False)
    monkeypatch.setattr(
        bench, "_probe_until",
        lambda *a, **k: {"backend": "tpu", "n": 1, "device_kind": "TPU v5e"},
    )
    monkeypatch.setenv("ACP_BENCH_TTFT", "0")
    monkeypatch.setenv("ACP_BENCH_AB", "0")
    monkeypatch.setenv("ACP_BENCH_TOTAL_BUDGET_S", "600")
    bench._parent()
    assert not lkg.exists(), "a stub/pytest run must never write last-known-good"


def test_lkg_refusal_rules(monkeypatch):
    """Each provenance rule individually, with the pytest guard removed so
    the downstream rules are actually reached."""
    good = {
        "value": 1234.5,
        "headline_note": "64/64 requests completed",
        "platform": {"backend": "tpu", "devices": 1},
    }
    # under pytest: refused regardless of content
    assert "pytest" in bench._lkg_refusal(good)
    monkeypatch.delenv("PYTEST_CURRENT_TEST", raising=False)
    assert bench._lkg_refusal(good) is None
    assert "stub" in bench._lkg_refusal({**good, "headline_note": "stub"})
    assert "headline" in bench._lkg_refusal({**good, "value": 0.0})
    assert "accelerator" in bench._lkg_refusal(
        {**good, "platform": {"backend": "cpu"}}
    )
    assert "accelerator" in bench._lkg_refusal({**good, "platform": {}})


def test_attach_ignores_poisoned_lkg_file(monkeypatch, tmp_path, capsys):
    """An LKG file written by an older bench.py with stub provenance (the
    actual r4 artifact) must not be surfaced into a new doc."""
    import json

    poisoned = tmp_path / "poisoned.json"
    poisoned.write_text(json.dumps({
        "value": 777.0, "headline_note": "stub",
        "platform": {"backend": "tpu", "device_kind": "TPU v5e"},
    }))
    monkeypatch.setenv("ACP_BENCH_LKG_PATH", str(poisoned))
    doc: dict = {}
    bench._attach_last_known_good(doc)
    assert "last_known_good" not in doc
    # a clean hardware doc still attaches
    clean = tmp_path / "clean.json"
    clean.write_text(json.dumps({
        "value": 1428.9, "headline_note": "64/64 requests completed",
        "platform": {"backend": "tpu", "device_kind": "TPU v5e"},
    }))
    monkeypatch.setenv("ACP_BENCH_LKG_PATH", str(clean))
    bench._attach_last_known_good(doc)
    assert doc["last_known_good"]["value"] == 1428.9


def test_flops_model_matches_hand_count():
    """The MFU denominator/numerator on a tiny known config: hand-counted
    matmul weights and attention-score FLOPs must agree exactly."""
    from types import SimpleNamespace

    c = SimpleNamespace(
        dim=8, n_layers=2, n_heads=2, n_kv_heads=1, head_dim=4,
        ffn_dim=16, vocab_size=32, n_experts=0, experts_per_token=0,
    )
    # per layer: Wq 8*8 + Wk 8*4 + Wv 8*4 + Wo 8*8 = 192; mlp 3*8*16 = 384
    # total: 2*(192+384) + lm_head 8*32 = 1408
    assert bench._matmul_params(c) == 1408.0
    # decode at ctx=10: 2*1408 + 4*2*2*4*10 = 2816 + 640
    assert bench._flops_per_token(c, 10.0) == 2816.0 + 640.0
    # MoE variant: active experts replace the dense FFN, router added
    cm = SimpleNamespace(
        dim=8, n_layers=2, n_heads=2, n_kv_heads=1, head_dim=4,
        ffn_dim=16, vocab_size=32, n_experts=4, experts_per_token=2,
    )
    # mlp: 3*8*16*2 + 8*4 = 800; total 2*(192+800) + 256 = 2240
    assert bench._matmul_params(cm) == 2240.0


def test_peak_flops_lookup():
    assert bench._peak_flops_per_chip("TPU v5e") == 197e12
    assert bench._peak_flops_per_chip("TPU v5 lite") == 197e12
    assert bench._peak_flops_per_chip("TPU v4") == 275e12
    assert bench._peak_flops_per_chip("cpu") is None
    assert bench._peak_flops_per_chip("") is None


def test_parent_surfaces_mfu_from_headline(stub_child, monkeypatch, capsys):
    import json

    stub_child(
        """
        print("MARK attach_ok 1", flush=True)
        print("MARK engine_built", flush=True)
        print("MARK warm_done", flush=True)
        print('RESULT headline {"tok_s_per_chip": 777.0, "mfu": 0.31, "note": "stub"}', flush=True)
        """
    )
    monkeypatch.setattr(bench, "_cpu_forced_inline", lambda: False)
    monkeypatch.setattr(
        bench, "_probe_until",
        lambda *a, **k: {"backend": "tpu", "n": 1, "device_kind": "TPU v5e"},
    )
    monkeypatch.setenv("ACP_BENCH_TTFT", "0")
    monkeypatch.setenv("ACP_BENCH_AB", "0")
    monkeypatch.setenv("ACP_BENCH_TOTAL_BUDGET_S", "600")
    bench._parent()
    lines = [
        json.loads(ln)
        for ln in capsys.readouterr().out.strip().splitlines()
        if ln.startswith("{")
    ]
    assert lines[-1]["mfu"] == 0.31


def test_parent_emits_json_line_even_when_run_raises(monkeypatch, capsys):
    """A parent-side crash must still print the one JSON line (driver
    contract) — the r01/r02 artifacts were unusable precisely because a
    failure path skipped the emit."""
    import json

    def boom(doc, notes):
        doc["value"] = 0.0
        raise RuntimeError("synthetic parent failure")

    monkeypatch.setattr(bench, "_parent_run", boom)
    bench._parent()
    out = capsys.readouterr().out.strip().splitlines()
    doc = json.loads(out[-1])
    assert doc["metric"] == "decode_tok_s_per_chip"


def test_burst_flops_counts_lm_head_once_per_prefill():
    """The engine's prefill computes logits only at the LAST prompt
    position, so the lm_head matmul must be charged once per prefill —
    charging it per prompt token overstates prefill FLOPs (and MFU)."""
    from types import SimpleNamespace

    c = SimpleNamespace(
        dim=8, n_layers=2, n_heads=2, n_kv_heads=1, head_dim=4,
        ffn_dim=16, vocab_size=32, n_experts=0, experts_per_token=0,
    )
    head = 2.0 * c.dim * c.vocab_size  # 512
    P = 10  # prompt_len
    per_tok = bench._flops_per_token(c, P / 2.0)
    # one prefill, no decode: P layer-tokens + ONE head matmul
    got = bench._burst_model_flops(c, P, prefills=1, gen_tokens=0, mean_ctx=0.0)
    assert got == P * (per_tok - head) + head
    assert got < P * per_tok  # strictly below the old per-token-head count
    # decode tokens still pay the head every step (they each sample)
    got2 = bench._burst_model_flops(c, P, prefills=1, gen_tokens=3, mean_ctx=12.0)
    assert got2 == got + 3 * bench._flops_per_token(c, 12.0)


def test_write_pr_doc_emits_and_respects_absence(tmp_path, monkeypatch):
    """ACP_BENCH_PR_DOC persists the final doc (per-PR perf trajectory);
    unset, nothing is written and the headline contract is untouched."""
    import json

    import bench

    doc = {"metric": "decode_tok_s_per_chip", "value": 1.0,
           "tool_turn": {"saved_pct": 42.0}}
    monkeypatch.delenv("ACP_BENCH_PR_DOC", raising=False)
    bench._write_pr_doc(doc)  # no env -> no-op, no crash

    path = tmp_path / "BENCH_PR999.json"
    monkeypatch.setenv("ACP_BENCH_PR_DOC", str(path))
    bench._write_pr_doc(doc)
    saved = json.loads(path.read_text())
    assert saved["tool_turn"]["saved_pct"] == 42.0
    assert saved["measured_at"]  # provenance stamp rides along
