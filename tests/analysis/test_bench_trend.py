"""bench-trend: the perf-trajectory sentinel (analysis/bench_trend.py).

A sentinel that can't trip detects nothing: fixtures synthesize a
BENCH_PR*.json trajectory and assert both directions — healthy trends
pass, regressions past tolerance exit nonzero — plus the robustness
posture (missing metrics skipped, cross-platform samples never compared,
CPU headline samples tabulated but not judged, unparseable docs skipped).
"""

import json
from pathlib import Path

from agentcontrolplane_tpu.analysis.__main__ import main as lint_main
from agentcontrolplane_tpu.analysis.bench_trend import (
    check_trend,
    load_docs,
    main as trend_main,
)


def _doc(tmp_path: Path, pr: int, **fields) -> None:
    (tmp_path / f"BENCH_PR{pr}.json").write_text(json.dumps(fields))


def test_load_docs_orders_by_pr_and_skips_garbage(tmp_path):
    _doc(tmp_path, 10, value=2.0)
    _doc(tmp_path, 2, value=1.0)
    (tmp_path / "BENCH_PR7.json").write_text("{not json")
    (tmp_path / "OTHER.json").write_text("{}")
    docs = load_docs(tmp_path)
    assert [pr for pr, _, _ in docs] == [2, 10]


def test_healthy_trajectory_passes(tmp_path):
    plat = {"backend": "tpu"}
    _doc(tmp_path, 6, value=1000.0, platform=plat)
    _doc(tmp_path, 7, value=1100.0, platform=plat,
         flight={"overhead_pct": 0.5})
    _doc(tmp_path, 9, value=980.0, platform=plat,  # within -35% of 1100
         flight={"overhead_pct": 0.8},
         prof={"overhead_pct": 0.4, "goodput_ratio": 0.8})
    table, regressions = check_trend(tmp_path)
    assert regressions == []
    assert "decode_tok_s_per_chip" in table and "PR9" in table
    assert trend_main(tmp_path) == 0


def test_headline_regression_past_tolerance_trips(tmp_path):
    plat = {"backend": "tpu"}
    _doc(tmp_path, 6, value=1000.0, platform=plat)
    _doc(tmp_path, 7, value=500.0, platform=plat)  # -50% > the 35% tol
    _, regressions = check_trend(tmp_path)
    assert [r.metric for r in regressions] == ["decode_tok_s_per_chip"]
    assert "BENCH_PR6.json" in regressions[0].detail
    assert trend_main(tmp_path) == 1


def test_cpu_headline_samples_are_tabulated_but_never_judged(tmp_path):
    """CPU fallback throughput varies with machine load and fixture knobs
    (the real docs show 100x spread) — absolute-throughput metrics only
    judge accelerator-backend samples."""
    _doc(tmp_path, 6, value=8000.0, platform={"backend": "cpu"})
    _doc(tmp_path, 7, value=75.0, platform={"backend": "cpu"})
    table, regressions = check_trend(tmp_path)
    assert regressions == []
    assert "8000.000" in table and "75.000" in table


def test_cross_platform_samples_never_compared(tmp_path):
    _doc(tmp_path, 6, value=8000.0, platform={"backend": "tpu"})
    _doc(tmp_path, 7, value=75.0, platform={"backend": "axon"})
    _, regressions = check_trend(tmp_path)
    assert regressions == []  # different accelerators: no baseline pair


def test_overhead_contract_ceiling_trips_absolutely(tmp_path):
    """The flight/prof overhead guards carry an absolute ceiling (their
    docs state a <2% contract; 3% is the noise-margin alarm) — one doc is
    enough to trip it, no baseline needed."""
    _doc(tmp_path, 12, platform={"backend": "cpu"},
         prof={"overhead_pct": 5.5})
    _, regressions = check_trend(tmp_path)
    assert [r.metric for r in regressions] == ["prof_overhead_pct"]
    assert "ceiling" in regressions[0].detail


def test_missing_metrics_and_empty_dir_are_skipped(tmp_path):
    _doc(tmp_path, 6, platform={"backend": "cpu"})  # no metrics at all
    _, regressions = check_trend(tmp_path)
    assert regressions == []
    empty = tmp_path / "empty"
    empty.mkdir()
    table, regressions = check_trend(empty)
    assert "no BENCH_PR" in table and regressions == []
    assert trend_main(empty) == 0


def test_runner_bench_trend_flag(tmp_path, capsys):
    _doc(tmp_path, 6, value=1000.0, platform={"backend": "tpu"})
    _doc(tmp_path, 7, value=400.0, platform={"backend": "tpu"})
    assert lint_main(["--bench-trend", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "regression" in out and "decode_tok_s_per_chip" in out
    # the repo's own trajectory is the advisory CI input: it must parse
    repo_root = Path(__file__).resolve().parents[2]
    assert lint_main(["--bench-trend", str(repo_root)]) in (0, 1)
