"""SLO envelope gate (analysis/slo_gate.py): structural per-scenario
judgement of bench scenario blocks — request conservation, outcome floors,
percentile sanity, and the ``--slo-envelopes`` CLI body. Stdlib-only."""

from __future__ import annotations

import json

from agentcontrolplane_tpu.analysis.slo_gate import (
    ENVELOPES,
    check_block,
    check_doc,
    main,
)


def good_block(**over):
    block = {
        "requests": 10, "completed": 10, "shed": 0, "cancelled": 0,
        "expired": 0, "errors": 0, "tool_calls": 0,
        "ttft_p50_ms": 12.0, "ttft_p99_ms": 30.0, "e2e_p50_ms": 40.0,
        "e2e_p99_ms": 90.0, "decode_stall_p99_ms": 8.0, "preempt_p99": 0.0,
        "wall_s": 1.0, "goodput_ratio": 0.8,
    }
    block.update(over)
    return block


def checks(scenario, block, arm="single"):
    return {v.check for v in check_block(scenario, arm, block)}


def test_healthy_storm_passes():
    assert check_block("persona_storm", "single", good_block()) == []


def test_conservation_violation_trips():
    assert "conservation" in checks(
        "persona_storm", good_block(completed=8)  # 2 requests vanished
    )


def test_errors_always_trip():
    got = checks("long_tail", good_block(completed=9, errors=1))
    assert "errors" in got and "conservation" not in got


def test_completed_ratio_floor():
    # persona_storm demands 100%; one shed request breaks its envelope
    # but would be fine for the long tail (floor 0.7)
    shedding = good_block(completed=9, shed=1)
    assert "completed_ratio" in checks("persona_storm", shedding)
    assert check_block("long_tail", "single", shedding) == []


def test_churn_must_churn():
    placid = good_block()
    got = checks("cancel_churn", placid)
    assert "cancelled" in got and "expired" in got
    churned = good_block(completed=5, cancelled=3, expired=2)
    assert check_block("cancel_churn", "single", churned) == []


def test_tool_swarm_requires_tool_calls():
    assert "tool_calls" in checks("tool_swarm", good_block())
    assert check_block(
        "tool_swarm", "single", good_block(tool_calls=10)
    ) == []


def test_percentile_and_goodput_sanity():
    assert "percentiles" in checks(
        "persona_storm", good_block(ttft_p99_ms=5.0)  # p99 < p50
    )
    assert "ttft" in checks(
        "persona_storm", good_block(ttft_p50_ms=0.0, ttft_p99_ms=0.0)
    )
    assert "goodput" in checks(
        "persona_storm", good_block(goodput_ratio=1.7)
    )


def test_unknown_scenario_uses_default_envelope():
    assert "completed_ratio" in checks(
        "brand_new_scenario", good_block(completed=4, shed=6)
    )


def test_every_shipped_scenario_has_an_envelope():
    from agentcontrolplane_tpu.scenarios import SCENARIOS

    assert set(ENVELOPES) == set(SCENARIOS)


def test_check_doc_renders_table_and_collects():
    doc = {
        "scenarios": {
            "persona_storm": {
                "single": good_block(),
                "fleet": good_block(completed=9, shed=1),  # trips ratio
            },
        }
    }
    lines, violations = check_doc(doc)
    assert any("scenario" in line for line in lines)  # header
    assert sum("persona_storm" in line for line in lines) == 2
    assert [v.arm for v in violations] == ["fleet"]


def test_check_doc_without_scenarios_is_calm():
    lines, violations = check_doc({"metric": "x"})
    assert violations == []
    assert "no scenario blocks" in lines[0]


def test_main_judges_newest_scenario_doc(tmp_path, capsys):
    (tmp_path / "BENCH_PR1.json").write_text(
        json.dumps({"metric": "old", "value": 1})
    )
    assert main(tmp_path) == 0
    assert "no bench doc with scenario blocks" in capsys.readouterr().out

    (tmp_path / "BENCH_PR2.json").write_text(json.dumps({
        "scenarios": {"persona_storm": {"single": good_block()}}
    }))
    assert main(tmp_path) == 0
    assert "judging BENCH_PR2.json" in capsys.readouterr().out

    (tmp_path / "BENCH_PR3.json").write_text(json.dumps({
        "scenarios": {"persona_storm": {"single": good_block(
            completed=3, shed=7
        )}}
    }))
    assert main(tmp_path) == 1
    out = capsys.readouterr().out
    assert "judging BENCH_PR3.json" in out  # newest doc wins
    assert "completed_ratio" in out
