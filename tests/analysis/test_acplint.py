"""acplint: the repo-custom static-analysis pass pack.

Two tier-1 gates plus per-rule negative fixtures:

- the whole package must lint clean (every declared contract holds in the
  shipped tree — this is the same gate ``make lint-acp`` / CI runs);
- the tests tree must lint clean too (no false positives on white-box
  test code);
- each rule has a minimal fixture that MUST fire, proving the pass
  actually detects its bug class (a lint that can't fail detects nothing).

The fixtures are deliberately tiny distillations of the real shipped bugs
each rule encodes (see docs/debugging-guide.md for the catalogue).
"""

import textwrap
from pathlib import Path

import agentcontrolplane_tpu
from agentcontrolplane_tpu.analysis import analyze
from agentcontrolplane_tpu.analysis.__main__ import main as lint_main

PKG_ROOT = Path(agentcontrolplane_tpu.__file__).parent
TESTS_ROOT = Path(__file__).resolve().parents[1]


def _write(tmp_path: Path, rel: str, source: str) -> Path:
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return tmp_path


def _rules(violations):
    return sorted(v.rule for v in violations)


# -- the two clean-tree gates -------------------------------------------------


def test_package_lints_clean():
    violations = analyze([PKG_ROOT])
    assert not violations, "\n".join(str(v) for v in violations)


def test_tests_tree_has_no_false_positives():
    violations = analyze([TESTS_ROOT])
    assert not violations, "\n".join(str(v) for v in violations)


def test_module_runner_exit_codes(tmp_path, capsys):
    assert lint_main(["--quiet", str(PKG_ROOT / "analysis")]) == 0
    root = _write(
        tmp_path,
        "models/bad.py",
        """
        import time

        def forward(x):
            return x * time.time()
        """,
    )
    assert lint_main(["--quiet", str(root)]) == 1
    out = capsys.readouterr().out
    assert "jit-purity" in out and "models/bad.py" in out


# -- rule: thread-ownership ---------------------------------------------------


def test_thread_ownership_fires_on_undeclared_cross_thread_access(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import threading

        class Engine:
            def __init__(self):
                self._ok = 0  # acp: mirror
                self._hidden = {}
                self._lock = threading.Lock()
                self._guarded = []

            def stats(self):  # acp: cross-thread
                n = self._ok            # mirror: fine
                m = len(self._hidden)   # atomic len: fine
                with self._lock:
                    g = list(self._guarded)  # lock held: fine
                bad = self._hidden      # undeclared read
                self._hidden = {}       # cross-thread write
                self._helper()          # undeclared helper call
                return n + m + len(g) + len(bad)

            def _helper(self):
                return 1
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"] * 3
    messages = " | ".join(v.message for v in violations)
    assert "read of engine-private self._hidden" in messages
    assert "WRITE to self._hidden" in messages
    assert "self._helper()" in messages


def test_thread_ownership_flags_cross_thread_writes_even_to_mirrors(tmp_path):
    """The mirror contract is atomic engine-side replacement, scrape-side
    READ — a cross-thread write to a declared mirror is still a write."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self._count = 0  # acp: mirror

            def stats(self):  # acp: cross-thread
                self._count = 0
                return self._count
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "WRITE to self._count" in violations[0].message


def test_missing_path_is_a_violation_not_a_silent_pass(tmp_path):
    """A lint gate pointed at a renamed/mistyped target must fail loudly,
    not exit 0 having linted nothing."""
    violations = analyze([tmp_path / "does_not_exist.py"])
    assert _rules(violations) == ["missing-path"]
    assert lint_main(["--quiet", str(tmp_path / "nope")]) == 1


def test_thread_ownership_fires_on_non_method_private_callable(tmp_path):
    """A private callable that is NOT a def in the class (instance-attr
    lambda, inherited method) can't be vetted as cross-thread — the
    attribute read itself must be held to the mirror rules."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self._snapshot = lambda: {}

            def stats(self):  # acp: cross-thread
                return self._snapshot()
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "self._snapshot" in violations[0].message


def test_thread_ownership_fires_on_server_scope_engine_reach(tmp_path):
    root = _write(
        tmp_path,
        "server/handlers.py",
        """
        def scrape(engine):
            return len(engine._slots)
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "scrape surface is stats()" in violations[0].message


def test_thread_ownership_fires_on_fleet_scope_engine_reach(tmp_path):
    """The fleet extension of the server-scope rule: router code drives
    many engines from router/caller threads, so an ``engine._*`` reach in
    ``fleet/`` is the same cross-thread ownership break as in ``server/``
    — the pool consumes submit()/stats() and the purpose-built public
    seams only."""
    root = _write(
        tmp_path,
        "fleet/router.py",
        """
        def route(engine):
            depth = len(engine._waiting)      # private reach: flagged
            ok = engine.stats()["waiting"]    # public surface: fine
            ok2 = engine.inject_host_kv(None) # public seam: fine
            return depth, ok, ok2
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "fleet code reaches" in violations[0].message
    assert "_waiting" in violations[0].message


def test_thread_ownership_fires_on_chained_server_scope_reach(tmp_path):
    """The flight recorder extension: reaching a PRIVATE through a public
    handle rooted at ``engine`` (engine.flight._events) is the same
    ownership break as engine._slots — the recorder's ring buffer is
    engine-written state and server code must use its declared
    cross-thread read methods."""
    root = _write(
        tmp_path,
        "server/handlers.py",
        """
        def scrape(engine):
            raw = engine.flight._events       # chained private reach
            ok = engine.flight.events()       # declared read method: fine
            ok2 = engine.stats()              # public surface: fine
            return len(raw), ok, ok2
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "_events" in violations[0].message


def test_thread_ownership_fires_on_profiler_scope_server_reach(tmp_path):
    """The compute-observatory extension of the chained-reach rule:
    ``engine.profiler`` is a public handle like ``engine.flight``, but its
    privates (the program table, the goodput ledger) are engine-written
    state — server code must go through the profiler's declared
    cross-thread read methods (``stats()`` / ``ledger()``), never
    ``engine.profiler._programs``."""
    root = _write(
        tmp_path,
        "server/handlers.py",
        """
        def perf(engine):
            raw = engine.profiler._programs    # chained private reach
            led = engine.profiler._goodput     # ledger privates too
            ok = engine.profiler.stats()       # declared read method: fine
            ok2 = engine.profiler.ledger()     # declared read method: fine
            return len(raw), led, ok, ok2
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"] * 2
    messages = " | ".join(v.message for v in violations)
    assert "_programs" in messages and "_goodput" in messages


def test_flight_recorder_cross_thread_reads_lint_clean(tmp_path):
    """The recorder's own posture — reads under its lock from methods
    declared cross-thread — must pass the pass that polices it."""
    root = _write(
        tmp_path,
        "flightish.py",
        """
        import threading

        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = []

            def record(self, kind):
                with self._lock:
                    self._events.append(kind)

            def events(self):  # acp: cross-thread
                with self._lock:
                    return list(self._events)

            def leaky(self):  # acp: cross-thread
                return list(self._events)  # no lock: must fire
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert violations[0].line > 0 and "_events" in violations[0].message


# -- rule: lane-defaults ------------------------------------------------------


def test_lane_defaults_fires_on_missing_and_uninitialized_lanes(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import numpy as np

        class Engine:
            def _verify_dispatch(self, W):  # acp: dispatch-lanes inputs,n_input,starts
                inputs = np.zeros((W, 4), dtype=np.int32)
                n_input = np.empty(W, dtype=np.int32)
                return inputs, n_input
        """,
    )
    violations = analyze([root])
    # np.empty itself + n_input (not ctor-built) + starts (never built)
    assert _rules(violations) == ["lane-defaults"] * 3
    messages = " | ".join(v.message for v in violations)
    assert "np.empty" in messages
    assert "'starts'" in messages and "'n_input'" in messages


def test_lane_defaults_accepts_tuple_assignments(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import numpy as np

        class Engine:
            def _dispatch(self, W):  # acp: dispatch-lanes toks,starts
                toks, starts = np.zeros(W), np.full(W, 64)
                return toks, starts
        """,
    )
    assert analyze([root]) == []


def test_lane_defaults_clean_when_all_lanes_defaulted(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import numpy as np

        class Engine:
            def _verify_dispatch(self, W):  # acp: dispatch-lanes inputs,n_input,starts
                inputs = np.zeros((W, 4), dtype=np.int32)
                n_input = np.zeros(W, dtype=np.int32)
                starts = np.full(W, 64, dtype=np.int32)
                return inputs, n_input, starts
        """,
    )
    assert analyze([root]) == []


# -- rule: jit-purity ---------------------------------------------------------


def test_jit_purity_fires_in_models_scope(tmp_path):
    root = _write(
        tmp_path,
        "models/net.py",
        """
        import time

        def forward(params, x):
            scale = time.monotonic()
            return x * scale
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["jit-purity"]
    assert "time.monotonic" in violations[0].message


def test_path_scoped_rules_bind_on_direct_file_arguments(tmp_path):
    """Linting a single file must keep its directory scope: a models/ file
    passed directly still gets the forward-body blanket."""
    root = _write(
        tmp_path,
        "models/net.py",
        """
        import time

        def forward(params, x):
            return x * time.time()
        """,
    )
    violations = analyze([root / "models" / "net.py"])
    assert _rules(violations) == ["jit-purity"]


def test_jit_purity_fires_on_jitted_functions_anywhere(tmp_path):
    root = _write(
        tmp_path,
        "anywhere.py",
        """
        import jax
        import random

        def impure(x):
            return x + random.random()

        f = jax.jit(impure)
        g = jax.jit(lambda x: x * random.random())
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["jit-purity"] * 2


# -- rule: coord-wallclock ----------------------------------------------------


def test_coord_wallclock_fires_on_unmarked_and_unguarded(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expire(self, deadline):
                return time.monotonic() > deadline

            def _expire_marked(self, deadline):  # acp: leader-local
                now = time.monotonic()
                return now > deadline

            def _expire_good(self, deadline):  # acp: leader-local
                if self._coord_follower:
                    return False
                return time.monotonic() > deadline
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["coord-wallclock"] * 2
    messages = " | ".join(v.message for v in violations)
    assert "not declared" in messages  # _expire: unmarked comparison
    assert "no follower guard" in messages  # _expire_marked: marker is a lie


def test_coord_wallclock_taints_derived_values(tmp_path):
    """'age = now - t0; if age > limit' is still a wall-clock decision —
    taint must propagate through derived assignments, not just the
    direct clock read."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expired(self, started_at, limit):
                now = time.monotonic()
                age = now - started_at
                return age > limit
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["coord-wallclock"]


def test_coord_wallclock_rejects_inverted_guard(tmp_path):
    """``if not self._coord_follower: return`` returns on the LEADER and
    runs the wall-clock decision on every follower — the exact divergence
    the rule exists to stop. It must not satisfy the guard check."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expire(self, deadline):  # acp: leader-local
                if not self._coord_follower:
                    return False
                return time.monotonic() > deadline
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["coord-wallclock"]
    assert "no follower guard" in violations[0].message


def test_coord_wallclock_ignores_uncoordinated_classes(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Plain:
            def expired(self, deadline):
                return time.monotonic() > deadline
        """,
    )
    assert analyze([root]) == []


# -- rule: budget-sharing -----------------------------------------------------


def test_budget_sharing_fires_outside_the_seam(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                budget = sl.sampling.max_tokens - 1
                if len(sl.generated) >= sl.sampling.max_tokens:
                    return 0
                return budget
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["budget-sharing"]
    assert "_verify" in violations[0].message


def test_dispatch_seam_fires_outside_declared_seams(tmp_path):
    """A compiled-program call (or alias) from an unmarked method of a
    seam-declaring class is a new dispatch site: the multi-dispatch
    regression the fused megastep exists to prevent."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _megastep_dispatch(self):  # acp: megastep-seam
                return self._jit_megastep(self.params)

            def _sneaky_extra_dispatch(self):
                return self._jit_decode(self.params)

            def _sneaky_alias(self):
                fn = self._jit_prefill
                return fn(self.params)
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["dispatch-seam", "dispatch-seam"]
    assert "_sneaky_extra_dispatch" in violations[0].message
    assert "_sneaky_alias" in violations[1].message


def test_dispatch_seam_allows_builder_stores_and_unmarked_classes(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _megastep_dispatch(self):  # acp: megastep-seam
                return self._jit_megastep(self.params)

            def _build_jitted(self):
                # Store context: assignment is construction, not dispatch
                self._jit_megastep = object()

        class NoSeamsDeclared:
            def dispatch(self):
                # a class with no declared seams is out of scope (the rule
                # binds where the megastep contract was adopted)
                return self._jit_anything(self.params)
        """,
    )
    assert analyze([root]) == []


def test_swap_stage_fires_outside_declared_surface(tmp_path):
    """A new stage site (swap_staged assigned mid-cycle) or restore-row
    landing (a _jit_swap_* load) from an unmarked method of a class that
    adopted the prefetch split bypasses its fault/teardown contract."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _stage_swap_in(self, sl):  # acp: swap-stage
                sl.swap_staged = {"groups": []}

            def _sneaky_stage(self, sl):
                sl.swap_staged = {"groups": [1]}

            def _sneaky_commit(self):
                fn = self._jit_swap_scatter
                return fn(self.cache)
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["swap-stage", "swap-stage"]
    assert "_sneaky_stage" in violations[0].message
    assert "_sneaky_commit" in violations[1].message


def test_swap_stage_allows_teardown_and_marked_methods(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _stage_swap_in(self, sl):  # acp: swap-stage
                sl.swap_staged = {"groups": []}

            def _swap_in_rows(self, slot):  # acp: megastep-seam
                # the blocking fallback is part of the declared surface
                return self._jit_swap_restore(self.cache)

            def _preempt(self, sl):
                # clearing a stage is teardown, not a copy — fault aborts
                # and slot teardown discard stages from anywhere
                sl.swap_staged = None

        class NeverAdoptedPrefetch:
            def restore(self, sl):
                # no swap-stage method declared: out of scope
                sl.swap_staged = {"groups": []}
        """,
    )
    assert analyze([root]) == []


# -- suppression pragma -------------------------------------------------------


def test_inline_pragma_suppresses_a_rule(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                return sl.sampling.max_tokens - 1  # acp-lint: disable=budget-sharing
        """,
    )
    assert analyze([root]) == []


def test_pragma_only_suppresses_the_named_rule(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                return sl.sampling.max_tokens - 1  # acp-lint: disable=jit-purity
        """,
    )
    assert _rules(analyze([root])) == ["budget-sharing"]


def test_parse_error_is_a_violation_not_a_crash(tmp_path):
    root = _write(tmp_path, "broken.py", "def f(:\n")
    assert _rules(analyze([root])) == ["parse-error"]


# -- metrics-docs drift check -------------------------------------------------


def test_metrics_docs_inventory_in_sync():
    """The shipped tree's gate: every acp_* metric registered in the
    package appears in docs/observability.md and vice versa (the same
    check ``make lint-acp`` runs via --metrics-docs)."""
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    doc = PKG_ROOT.parent / "docs" / "observability.md"
    violations = check_metrics_docs(PKG_ROOT, doc)
    assert not violations, "\n".join(str(v) for v in violations)


def test_metrics_docs_fires_both_drift_directions(tmp_path):
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "from x import REGISTRY\n"
        'REGISTRY.counter_add("acp_documented_total", 1.0)\n'
        'REGISTRY.gauge_set("acp_undocumented_gauge", 2.0)\n'
    )
    doc = tmp_path / "inv.md"
    doc.write_text("- `acp_documented_total` — fine.\n- `acp_ghost_total` — gone.\n")
    rules = sorted(
        (v.rule, "missing" if "missing from" in v.message else "stale")
        for v in check_metrics_docs(pkg, doc)
    )
    assert rules == [("metrics-docs", "missing"), ("metrics-docs", "stale")]


def test_metrics_docs_flags_dynamic_names_and_skips_non_registry(tmp_path):
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "from x import REGISTRY\n"
        "name = 'acp_' + kind\n"
        "REGISTRY.counter_add(name, 1.0)\n"      # dynamic: must fire
        "controller.observe(prop, acc)\n"        # not REGISTRY: ignored
    )
    doc = tmp_path / "inv.md"
    doc.write_text("nothing\n")
    violations = check_metrics_docs(pkg, doc)
    assert len(violations) == 1
    assert "non-literal metric name" in violations[0].message


def test_metrics_docs_missing_doc_is_a_violation(tmp_path):
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    violations = check_metrics_docs(pkg, tmp_path / "nope.md")
    assert len(violations) == 1 and "does not exist" in violations[0].message


def test_runner_metrics_docs_flag(tmp_path, capsys):
    doc = PKG_ROOT.parent / "docs" / "observability.md"
    assert lint_main([
        "--quiet", "--metrics-docs", str(doc), str(PKG_ROOT / "analysis")
    ]) == 0
    stale = tmp_path / "stale.md"
    stale.write_text("- `acp_engine_never_registered_total`\n")
    assert lint_main([
        "--quiet", "--metrics-docs", str(stale), str(PKG_ROOT / "analysis")
    ]) == 1
    assert "metrics-docs" in capsys.readouterr().out


def test_rule_scoped_run_skips_metrics_docs(tmp_path, capsys):
    """Review fix: --rule scoping must not fail on inventory drift the
    caller didn't ask about."""
    stale = tmp_path / "stale.md"
    stale.write_text("- `acp_engine_never_registered_total`\n")
    assert lint_main([
        "--quiet", "--rule", "jit-purity", "--metrics-docs", str(stale),
        str(PKG_ROOT / "analysis"),
    ]) == 0


# -- rule: donated-after-dispatch (PR 13 stale-capture class) -----------------


def test_donated_dispatch_fires_on_stale_capture(tmp_path):
    """The re-introduced PR 13 bug, distilled: an argument pack captures
    ``self.cache``, a donating fallback runs, and the pack re-dispatches
    without re-capture — the buffer it holds was donated (deleted)."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self.cache = init()  # acp: donated

            def _chunk_dispatch(self, ln):
                self.cache = self._jit_chunk(self.cache, ln)

            def _verify(self, pending):  # acp: megastep-seam
                args = [self.params, self.cache, self.extra]
                if pending:
                    self._chunk_dispatch(pending)
                cache, toks = self._jit_verify(*args)
                self.cache = cache
        """,
    )
    violations = analyze([root], rules=["donated-after-dispatch"])
    assert _rules(violations) == ["donated-after-dispatch"]
    assert "'args' captures donated state" in violations[0].message
    assert "re-capture" in violations[0].message


def test_donated_dispatch_clean_with_recapture(tmp_path):
    """The shipped one-line fix: ``args[1] = self.cache`` after the
    fallback re-captures the fresh buffer, so the re-dispatch is legal."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self.cache = init()  # acp: donated

            def _chunk_dispatch(self, ln):
                self.cache = self._jit_chunk(self.cache, ln)

            def _verify(self, pending):  # acp: megastep-seam
                args = [self.params, self.cache, self.extra]
                if pending:
                    self._chunk_dispatch(pending)
                    args[1] = self.cache
                cache, toks = self._jit_verify(*args)
                self.cache = cache
        """,
    )
    assert analyze([root], rules=["donated-after-dispatch"]) == []


def test_donated_dispatch_clean_without_intervening_donation(tmp_path):
    """A capture that dispatches straight away (no donating statement on
    any path in between) is the normal dispatch idiom, never flagged —
    and direct ``self.cache`` reads AT the call site are always fresh."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self.cache = init()  # acp: donated

            def _decode(self):  # acp: megastep-seam
                args = [self.params, self.cache]
                cache, toks = self._jit_decode(*args)
                self.cache = cache

            def _direct(self, ln):  # acp: megastep-seam
                self.cache = self._jit_chunk(self.cache, ln)
                out = self._jit_probe(self.cache)
                return out
        """,
    )
    assert analyze([root], rules=["donated-after-dispatch"]) == []


def test_donated_dispatch_resurrects_pr13_bug_in_real_engine(tmp_path):
    """The historical-bug gate: delete the shipped fix (``args[1] =
    self.cache`` after the spec-verify fallback) from the REAL engine
    source and the rule must fire; the shipped source must stay clean."""
    src = (PKG_ROOT / "engine" / "engine.py").read_text()
    fix = "            args[1] = self.cache\n"
    assert fix in src, "the PR 13 re-capture moved; update this fixture"
    assert analyze(
        [PKG_ROOT / "engine" / "engine.py"], rules=["donated-after-dispatch"]
    ) == []
    broken = tmp_path / "engine_pr13.py"
    broken.write_text(src.replace(fix, ""))
    violations = analyze([broken], rules=["donated-after-dispatch"])
    assert violations, "removing the PR 13 fix must re-fire the rule"
    assert all(v.rule == "donated-after-dispatch" for v in violations)
    assert any("'args'" in v.message for v in violations)


# -- rule: kv-leaf-completeness (PR 14 scale-shear class) ---------------------


def test_kv_leaf_fires_on_scale_dropping_extract(tmp_path):
    """The re-introduced PR 14 bug: an extract that moves only the "k"/"v"
    leaves — a quantized cache's ks/vs scale rows would be sheared off."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _extract_rows(self, slot, cut):  # acp: kv-seam
                return {
                    "k": self.cache["k"][:, slot, :cut],
                    "v": self.cache["v"][:, slot, :cut],
                }
        """,
    )
    violations = analyze([root], rules=["kv-leaf-completeness"])
    assert len(violations) == 4  # two dict keys + two subscripts
    assert all(v.rule == "kv-leaf-completeness" for v in violations)
    assert "sheared" in violations[0].message


def test_kv_leaf_clean_with_generic_iteration_or_twins(tmp_path):
    """Both escapes: dict-generic iteration (new leaves ride for free) or
    explicit ks/vs twin handling. A bare cache["k"] shape probe stays
    legal beside generic iteration."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _extract_rows(self, slot, cut):  # acp: kv-seam
                rows = {n: a[:, slot, :cut] for n, a in self.cache.items()}
                probe = self.cache["k"].shape
                return rows

            def _swap_in_rows(self, slot, entry):  # acp: kv-seam
                self.cache["k"] = entry["k"]
                self.cache["v"] = entry["v"]
                if "ks" in entry:
                    self.cache["ks"] = entry["ks"]
                    self.cache["vs"] = entry["vs"]
        """,
    )
    assert analyze([root], rules=["kv-leaf-completeness"]) == []


def test_kv_leaf_flags_marker_with_no_leaf_handling(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _swap_out(self, slot):  # acp: kv-seam
                return self._budget - slot
        """,
    )
    violations = analyze([root], rules=["kv-leaf-completeness"])
    assert _rules(violations) == ["kv-leaf-completeness"]
    assert "marker is a lie" in violations[0].message


def test_kv_leaf_ignores_unmarked_functions(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def probe(self):
                return self.cache["k"].shape
        """,
    )
    assert analyze([root], rules=["kv-leaf-completeness"]) == []


# -- rule: resolve-after-record (PR 9 record-before-resolution) ---------------


def test_resolve_record_fires_on_resolve_before_record(tmp_path):
    """The reorder the PR 9 prose rule forbids: set_result hoisted above
    flight.finish — a caller querying the timeline at result() races."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _finish(self, sl, res):
                sl.request.future.set_result(res)
                self.flight.finish(sl.request.rid, res)
        """,
    )
    violations = analyze([root], rules=["resolve-after-record"])
    assert _rules(violations) == ["resolve-after-record"]
    assert "record BEFORE resolution" in violations[0].message


def test_resolve_record_clean_when_finish_precedes(tmp_path):
    """The shipped ordering, including the prewarm-guarded finish (strict
    domination is NOT required — ordering is the contract) and a local
    bound from the future attribute (def-use chains must see through it)."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _finish(self, sl, res):
                if not sl.request.prewarm:
                    self.flight.finish(sl.request.rid, res)
                fut = sl.request.future
                fut.set_result(res)
        """,
    )
    assert analyze([root], rules=["resolve-after-record"]) == []


def test_resolve_record_tracks_future_locals(tmp_path):
    """A resolution through a LOCAL the def-use chains trace to a future
    read must still be ordered after the record."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _fail(self, sl, err):
                fut = sl.request.future
                fut.set_exception(err)
                self.flight.finish(sl.request.rid, None)
        """,
    )
    violations = analyze([root], rules=["resolve-after-record"])
    assert _rules(violations) == ["resolve-after-record"]


def test_resolve_record_skips_functions_without_finish(tmp_path):
    """Sheds/expiries resolve without a terminal record by design — a
    function with no flight.finish call is out of scope."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _shed(self, sl, err):
                sl.request.future.set_exception(err)
        """,
    )
    assert analyze([root], rules=["resolve-after-record"]) == []


# -- rule: mirror-publish (PR 11 sweep-without-dispatch class) ----------------


def test_mirror_publish_fires_on_publish_skipping_sweep(tmp_path):
    """The re-introduced PR 11 bug: the idle loop sweeps (frees pages
    transitively) then parks without republishing — mirrors advertise
    pages that no longer exist until the next request arrives."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _release(self, slot):
                self._allocator.free(self._slot_pages.pop(slot))

            def _sweep(self):
                for slot in list(self._parked):
                    self._release(slot)

            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    self._sweep()
                    if not self._has_work():
                        continue
                    self._dispatch_once()
                    self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    violations = analyze([root], rules=["mirror-publish"])
    assert _rules(violations) == ["mirror-publish"]
    assert "idle-loop back edge" in violations[0].message


def test_mirror_publish_clean_when_idle_path_publishes(tmp_path):
    """The shipped fix: publish on the idle path too — every route from
    the mutation back to the loop head passes a publish."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _release(self, slot):
                self._allocator.free(self._slot_pages.pop(slot))

            def _sweep(self):
                for slot in list(self._parked):
                    self._release(slot)

            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    self._sweep()
                    if not self._has_work():
                        self._publish_memory_state()
                        continue
                    self._dispatch_once()
                    self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    assert analyze([root], rules=["mirror-publish"]) == []


def test_mirror_publish_flags_marker_with_no_publish(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    self._host_pool.put("x", 1)

            def _publish_memory_state(self):
                self._mirror = 0
        """,
    )
    violations = analyze([root], rules=["mirror-publish"])
    assert _rules(violations) == ["mirror-publish"]
    assert "never calls" in violations[0].message


def test_mirror_publish_exempts_bounded_drains(tmp_path):
    """for-loops and post-loop teardown never return to idle — only the
    while-loop back edge is the gap the rule exists to close."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _drain(self):  # acp: idle-loop
                for slot in list(self._parked):
                    self._allocator.free(self._slot_pages.pop(slot))
                self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    assert analyze([root], rules=["mirror-publish"]) == []


def test_mirror_publish_resurrects_pr11_bug_in_real_engine(tmp_path):
    """The historical-bug gate: delete the idle-path publish (the shipped
    PR 11 fix) from the REAL engine source and the rule must fire; the
    shipped source must stay clean."""
    src = (PKG_ROOT / "engine" / "engine.py").read_text()
    fix = (
        "                        self._publish_memory_state()\n"
        "                        continue\n"
    )
    assert fix in src, "the PR 11 idle-path publish moved; update this fixture"
    assert analyze(
        [PKG_ROOT / "engine" / "engine.py"], rules=["mirror-publish"]
    ) == []
    broken = tmp_path / "engine_pr11.py"
    broken.write_text(src.replace(fix, "                        continue\n"))
    violations = analyze([broken], rules=["mirror-publish"])
    assert violations, "removing the PR 11 fix must re-fire the rule"
    assert all(v.rule == "mirror-publish" for v in violations)


# -- coord-wallclock v1→v2 migration pin --------------------------------------


def test_coord_wallclock_migration_findings_pinned():
    """The migration proof: coord-wallclock now rides the shared
    ``core.taint_fixpoint`` lattice; its findings over a composite of the
    v1 fixture shapes are pinned byte-identical (path:line:rule:message),
    so a lattice change that shifts this rule's output fails loudly."""
    import textwrap as _tw

    src = _tw.dedent(
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expire(self, deadline):
                return time.monotonic() > deadline

            def _expire_marked(self, deadline):  # acp: leader-local
                now = time.monotonic()
                return now > deadline

            def _derived(self, started_at, limit):
                now = time.monotonic()
                age = now - started_at
                return age > limit

            def _inverted(self, deadline):  # acp: leader-local
                if not self._coord_follower:
                    return False
                return time.monotonic() > deadline

            def _expire_good(self, deadline):  # acp: leader-local
                if self._coord_follower:
                    return False
                return time.monotonic() > deadline

            def _metric(self, t0, hist):
                hist.observe(time.monotonic() - t0)
        """
    )
    from agentcontrolplane_tpu.analysis.core import SourceFile
    from agentcontrolplane_tpu.analysis.passes import CoordWallclockPass

    sf = SourceFile("eng.py", src, relpath="eng.py")
    found = [str(v) for v in CoordWallclockPass().run(sf)]
    assert found == [
        "eng.py:9: [coord-wallclock] wall-clock comparison in _expire, "
        "which is not declared '# acp: leader-local' — coordinated ranks "
        "would diverge on local clocks (route the decision through the "
        "leader seam)",
        "eng.py:11: [coord-wallclock] _expire_marked is declared "
        "'# acp: leader-local' but has no follower guard (if "
        "self._coord_follower: return) — followers would fork lockstep "
        "on their local clock",
        "eng.py:18: [coord-wallclock] wall-clock comparison in _derived, "
        "which is not declared '# acp: leader-local' — coordinated ranks "
        "would diverge on local clocks (route the decision through the "
        "leader seam)",
        "eng.py:20: [coord-wallclock] _inverted is declared "
        "'# acp: leader-local' but has no follower guard (if "
        "self._coord_follower: return) — followers would fork lockstep "
        "on their local clock",
    ]


# -- the flow framework (core) ------------------------------------------------


def test_flowgraph_ordering_queries():
    import ast as _ast

    from agentcontrolplane_tpu.analysis.core import FlowGraph

    src = textwrap.dedent(
        """
        def f(xs):
            a = 1
            while xs:
                b = 2
                if cond():
                    c = 3
                    continue
                d = 4
            e = 5
        """
    )
    fn = _ast.parse(src).body[0]
    g = FlowGraph(fn)
    by_line = {s.lineno: s for s in g.stmts}
    a, loop, b, c, d, e = (by_line[n] for n in (3, 4, 5, 7, 9, 10))
    assert g.reachable_after(a, e)
    assert g.reachable_after(b, b)          # loop back edge
    assert g.reachable_after(c, loop)       # continue returns to the head
    assert not g.reachable_after(e, a)      # no path backwards out of exit
    assert not g.reachable_after(e, b)      # the loop is never re-entered
    assert g.reachable_after(c, d)          # via the back edge, next iteration
    assert g.exists_path(b, loop, avoiding=[])
    assert not g.exists_path(b, loop, avoiding=[c, d])  # both arms blocked


def test_taint_fixpoint_propagates_through_derived_bindings():
    import ast as _ast

    from agentcontrolplane_tpu.analysis.core import taint_fixpoint

    src = textwrap.dedent(
        """
        def f(t0):
            now = clock()
            age = now - t0
            msg = "age=%s" % age
            clean = t0 + 1
            self.field = now
        """
    )
    fn = _ast.parse(src).body[0]
    tainted = taint_fixpoint(
        fn,
        lambda n: isinstance(n, _ast.Call)
        and isinstance(n.func, _ast.Name)
        and n.func.id == "clock",
    )
    assert tainted == {"now", "age", "msg"}  # attribute store never taints


def test_collect_suppressions_counts_comments_not_strings(tmp_path):
    from agentcontrolplane_tpu.analysis.core import collect_suppressions

    _write(
        tmp_path,
        "a.py",
        """
        x = 1  # justified: fixture  # acp-lint: disable=jit-purity
        s = "text with # acp-lint: disable=jit-purity inside a string"
        """,
    )
    sups = collect_suppressions([tmp_path])
    assert len(sups) == 1
    assert sups[0].path == "a.py" and sups[0].rules == ("jit-purity",)


# -- runner: --json / --timing / --suppression-budget -------------------------


def test_runner_json_findings_doc(tmp_path, capsys):
    root = _write(
        tmp_path,
        "models/bad.py",
        """
        import time

        def forward(x):
            return x * time.time()  # acp-lint: disable=coord-wallclock
        """,
    )
    import json as _json

    out = tmp_path / "findings.json"
    assert lint_main(["--quiet", "--json", str(out), str(root)]) == 1
    doc = _json.loads(out.read_text())
    assert doc["version"] == 1
    assert doc["counts"]["violations"] == 1
    assert doc["counts"]["by_rule"] == {"jit-purity": 1}
    assert doc["counts"]["rules_total"] == 11
    assert doc["counts"]["suppressions_total"] == 1
    [v] = doc["violations"]
    assert v["rule"] == "jit-purity" and v["path"] == "models/bad.py"
    assert isinstance(v["line"], int) and "host call" in v["message"]
    [s] = doc["suppressions"]
    assert s["rules"] == ["coord-wallclock"]
    capsys.readouterr()


def test_runner_json_to_stdout(tmp_path, capsys):
    import json as _json

    root = _write(tmp_path, "clean.py", "x = 1\n")
    assert lint_main(["--quiet", "--json", "-", str(root)]) == 0
    doc = _json.loads(capsys.readouterr().out)
    assert doc["counts"]["violations"] == 0


def test_runner_timing_report_and_budget(tmp_path, capsys):
    root = _write(tmp_path, "clean.py", "x = 1\n")
    assert lint_main(["--quiet", "--timing", str(root)]) == 0
    err = capsys.readouterr().err
    assert "acplint timing" in err and "total" in err
    for rule in ("jit-purity", "donated-after-dispatch", "mirror-publish"):
        assert rule in err  # every requested rule reports, even at ~0s
    # an impossible budget must flip the exit code even on a clean tree
    assert lint_main([
        "--quiet", "--timing-budget", "0", str(root)
    ]) == 1
    assert "TIMING BUDGET EXCEEDED" in capsys.readouterr().err


def test_runner_suppression_budget_gate(tmp_path, capsys):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                return sl.sampling.max_tokens - 1  # fixture debt  # acp-lint: disable=budget-sharing
        """,
    )
    assert lint_main(["--quiet", "--suppression-budget", "1", str(root)]) == 0
    capsys.readouterr()
    assert lint_main(["--quiet", "--suppression-budget", "0", str(root)]) == 1
    err = capsys.readouterr().err
    assert "SUPPRESSION DEBT OVER BUDGET" in err
    assert "disable=budget-sharing" in err  # the justification list prints


def test_tree_suppression_debt_within_pinned_budget():
    """The same pin make lint-acp / CI enforce (ACP_LINT_SUPPRESSIONS):
    growth is a deliberate act taken in the PR that adds the pragma, never
    drift. If this fails, either remove the new suppression or raise the
    budget here, in the Makefile, and in ci.yml — in the same change."""
    from agentcontrolplane_tpu.analysis.core import collect_suppressions

    sups = collect_suppressions([PKG_ROOT, TESTS_ROOT])
    listing = "\n".join(str(s) for s in sups)
    assert len(sups) <= 4, f"suppression debt grew:\n{listing}"


def test_mirror_publish_fires_on_direct_inline_mutation(tmp_path):
    """Verify-drive regression: a page free written INLINE in the idle
    loop (no helper method) must anchor a violation too — the statement
    scan covers direct allocator/pool mutations, not just calls into
    mutating methods."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    self._allocator.free(self._expired_pages())
                    if not self._has_work():
                        continue
                    self._dispatch_once()
                    self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    violations = analyze([root], rules=["mirror-publish"])
    assert _rules(violations) == ["mirror-publish"]
    assert "idle-loop back edge" in violations[0].message


def test_mirror_publish_try_else_block_is_not_a_raise_path(tmp_path):
    """Review regression: only try-BODY statements may raise into their
    handlers. A free in the ``else`` block runs past them — every real
    path hits the publish below, so this loop is clean (the CFG used to
    wire spurious else→handler edges and flag it)."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    try:
                        batch = self._poll()
                    except TimeoutError:
                        continue
                    else:
                        self._allocator.free(batch)
                    self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    assert analyze([root], rules=["mirror-publish"]) == []


def test_mirror_publish_try_body_raise_path_still_counts(tmp_path):
    """The dual pin: a mutation IN the try body can raise into a handler
    whose ``continue`` skips the publish — that escape path must keep
    firing (the CFG is deliberately coarse about which calls raise)."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    try:
                        self._allocator.free(self._expired_pages())
                    except TimeoutError:
                        continue
                    self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    violations = analyze([root], rules=["mirror-publish"])
    assert _rules(violations) == ["mirror-publish"]


def test_kv_leaf_list_loop_does_not_exempt_literal_leaves(tmp_path):
    """Review regression: a for-loop over an unrelated LIST (``for ch in
    chunks:``) is not generic leaf iteration — hardcoded "k"/"v" copies
    inside it are exactly the PR 14 shear shape and must fire. Bare-name
    iteration still qualifies when the loop variable is used as a KEY."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _extract_pages(self, chunks):  # acp: kv-seam
                out = {}
                for ch in chunks:
                    out["k"] = ch["k"]
                    out["v"] = ch["v"]
                return out

            def _merge(self, chunks):  # acp: kv-seam
                out = {}
                for name in self.cache:
                    out[name] = [ch[name] for ch in chunks]
                return out
        """,
    )
    violations = analyze([root], rules=["kv-leaf-completeness"])
    assert violations and all(v.rule == "kv-leaf-completeness" for v in violations)
    assert all(v.line < 9 for v in violations), "_merge must stay clean"


def test_donated_dispatch_fires_on_loop_carried_self_donation(tmp_path):
    """Review regression: when the donate and the use share ONE statement
    inside a loop, the back edge makes iteration N's donation precede
    iteration N+1's use — the second dispatch consumes a deleted buffer.
    A re-capture inside the loop body makes it legal again."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self.cache = init()  # acp: donated

            def _fallback(self, chunks):  # acp: megastep-seam
                args = [self.params, self.cache]
                for ln in chunks:
                    self.cache = self._jit_chunk(*args)

            def _fallback_ok(self, chunks):  # acp: megastep-seam
                args = [self.params, self.cache]
                for ln in chunks:
                    self.cache = self._jit_chunk(*args)
                    args[1] = self.cache
        """,
    )
    violations = analyze([root], rules=["donated-after-dispatch"])
    assert _rules(violations) == ["donated-after-dispatch"]
    assert violations[0].line == 9, "_fallback_ok must stay clean"


def test_json_stdout_stays_parseable_with_violations(tmp_path, capsys):
    """Review regression: ``--json -`` owns stdout. With findings present
    the human violation lines move to stderr, so downstream tooling can
    always ``json.loads`` the payload — exactly the case (failure) where
    CI consumes it."""
    import json as _json

    root = _write(
        tmp_path,
        "models/bad.py",
        """
        import time

        def forward(x):
            return x * time.time()
        """,
    )
    assert lint_main(["--quiet", "--json", "-", str(root)]) == 1
    captured = capsys.readouterr()
    doc = _json.loads(captured.out)
    assert doc["counts"]["violations"] == 1
    assert "jit-purity" in captured.err


def test_mirror_publish_fires_without_publish_method_defined(tmp_path):
    """Review regression: a class whose idle loop never calls the publish
    hook must fire even when the class doesn't DEFINE
    _publish_memory_state — a rename of the hook must not silently turn
    the rule off (call sites are what the pass scans, so an inherited
    publisher still counts)."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    self._allocator.free(self._expired_pages())
        """,
    )
    violations = analyze([root], rules=["mirror-publish"])
    assert _rules(violations) == ["mirror-publish"]
    assert "never calls" in violations[0].message


def test_donated_dispatch_augassign_is_not_a_recapture(tmp_path):
    """Review regression: ``args += [...]`` extends the capture list IN
    PLACE — the stale donated buffer survives it, so it must not count as
    a re-capture blocker."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self.cache = init()  # acp: donated

            def _chunk_dispatch(self, ln):
                self.cache = self._jit_chunk(self.cache, ln)

            def _verify(self, pending):  # acp: megastep-seam
                args = [self.params, self.cache, self.extra]
                if pending:
                    self._chunk_dispatch(pending)
                    args += [self.flag]
                cache, toks = self._jit_verify(*args)
                self.cache = cache
        """,
    )
    violations = analyze([root], rules=["donated-after-dispatch"])
    assert _rules(violations) == ["donated-after-dispatch"]
    assert "'args' captures donated state" in violations[0].message


def test_resolve_record_ignores_flight_lookalike_chains(tmp_path):
    """Review regression: 'inflight.finish'/'preflight.finish' are not the
    flight recorder. A lookalike must neither pull a function into scope
    (false positive) nor count as the required record when a real
    flight.finish sits after the resolution (false negative)."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _done(self, req):
                self.inflight.finish(req.rid)
                req.future.set_result(1)

            def _late(self, req):
                self.preflight.finish(req.rid)
                req.future.set_result(1)
                self.flight.finish(req.rid, 1)
        """,
    )
    violations = analyze([root], rules=["resolve-after-record"])
    assert _rules(violations) == ["resolve-after-record"]
    assert violations[0].line == 9, "_done must stay out of scope"


def test_resolve_record_closure_only_finish_is_out_of_scope(tmp_path):
    """Review regression: a flight.finish living only inside a nested
    callback anchors nowhere in THIS function's CFG — the function is out
    of scope, not a guaranteed violation on every resolution."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _done(self, req):
                def _cb():
                    req.flight.finish("ok")
                self.pool.submit(_cb)
                req.future.set_result(1)
        """,
    )
    assert analyze([root], rules=["resolve-after-record"]) == []


def test_mirror_publish_continue_runs_the_finally_first(tmp_path):
    """Review regression: a ``continue`` leaving a try body runs the
    ``finally`` before reaching the loop head — a publish living in the
    finally covers every such path (the CFG used to wire continue straight
    to the back edge, bypassing it). Without the publish the escape still
    fires."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _run(self):  # acp: idle-loop
                while not self._stopping:
                    try:
                        self._allocator.free(self._expired_pages())
                        if not self._has_work():
                            continue
                        self._dispatch_once()
                    finally:
                        self._publish_memory_state()

            def _bare(self):  # acp: idle-loop
                while not self._stopping:
                    try:
                        self._allocator.free(self._expired_pages())
                        if not self._has_work():
                            continue
                        self._dispatch_once()
                    finally:
                        self._log_cycle()
                    self._publish_memory_state()

            def _publish_memory_state(self):
                self._pages_mirror = self._allocator.pages_free
        """,
    )
    violations = analyze([root], rules=["mirror-publish"])
    assert _rules(violations) == ["mirror-publish"]
    assert violations[0].line > 12, "_run (publish in finally) must be clean"


def test_resolve_record_return_routes_through_finally_finish(tmp_path):
    """The same CFG fix seen from resolve-after-record: an early return
    runs the finally, so a flight.finish there precedes a resolution made
    by the caller path below the try."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _done(self, req, res):
                try:
                    if res is None:
                        return
                finally:
                    self.flight.finish(req.rid, res)
                req.future.set_result(res)
        """,
    )
    assert analyze([root], rules=["resolve-after-record"]) == []
