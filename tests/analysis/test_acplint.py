"""acplint: the repo-custom static-analysis pass pack.

Two tier-1 gates plus per-rule negative fixtures:

- the whole package must lint clean (every declared contract holds in the
  shipped tree — this is the same gate ``make lint-acp`` / CI runs);
- the tests tree must lint clean too (no false positives on white-box
  test code);
- each rule has a minimal fixture that MUST fire, proving the pass
  actually detects its bug class (a lint that can't fail detects nothing).

The fixtures are deliberately tiny distillations of the real shipped bugs
each rule encodes (see docs/debugging-guide.md for the catalogue).
"""

import textwrap
from pathlib import Path

import agentcontrolplane_tpu
from agentcontrolplane_tpu.analysis import analyze
from agentcontrolplane_tpu.analysis.__main__ import main as lint_main

PKG_ROOT = Path(agentcontrolplane_tpu.__file__).parent
TESTS_ROOT = Path(__file__).resolve().parents[1]


def _write(tmp_path: Path, rel: str, source: str) -> Path:
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return tmp_path


def _rules(violations):
    return sorted(v.rule for v in violations)


# -- the two clean-tree gates -------------------------------------------------


def test_package_lints_clean():
    violations = analyze([PKG_ROOT])
    assert not violations, "\n".join(str(v) for v in violations)


def test_tests_tree_has_no_false_positives():
    violations = analyze([TESTS_ROOT])
    assert not violations, "\n".join(str(v) for v in violations)


def test_module_runner_exit_codes(tmp_path, capsys):
    assert lint_main(["--quiet", str(PKG_ROOT / "analysis")]) == 0
    root = _write(
        tmp_path,
        "models/bad.py",
        """
        import time

        def forward(x):
            return x * time.time()
        """,
    )
    assert lint_main(["--quiet", str(root)]) == 1
    out = capsys.readouterr().out
    assert "jit-purity" in out and "models/bad.py" in out


# -- rule: thread-ownership ---------------------------------------------------


def test_thread_ownership_fires_on_undeclared_cross_thread_access(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import threading

        class Engine:
            def __init__(self):
                self._ok = 0  # acp: mirror
                self._hidden = {}
                self._lock = threading.Lock()
                self._guarded = []

            def stats(self):  # acp: cross-thread
                n = self._ok            # mirror: fine
                m = len(self._hidden)   # atomic len: fine
                with self._lock:
                    g = list(self._guarded)  # lock held: fine
                bad = self._hidden      # undeclared read
                self._hidden = {}       # cross-thread write
                self._helper()          # undeclared helper call
                return n + m + len(g) + len(bad)

            def _helper(self):
                return 1
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"] * 3
    messages = " | ".join(v.message for v in violations)
    assert "read of engine-private self._hidden" in messages
    assert "WRITE to self._hidden" in messages
    assert "self._helper()" in messages


def test_thread_ownership_flags_cross_thread_writes_even_to_mirrors(tmp_path):
    """The mirror contract is atomic engine-side replacement, scrape-side
    READ — a cross-thread write to a declared mirror is still a write."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self._count = 0  # acp: mirror

            def stats(self):  # acp: cross-thread
                self._count = 0
                return self._count
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "WRITE to self._count" in violations[0].message


def test_missing_path_is_a_violation_not_a_silent_pass(tmp_path):
    """A lint gate pointed at a renamed/mistyped target must fail loudly,
    not exit 0 having linted nothing."""
    violations = analyze([tmp_path / "does_not_exist.py"])
    assert _rules(violations) == ["missing-path"]
    assert lint_main(["--quiet", str(tmp_path / "nope")]) == 1


def test_thread_ownership_fires_on_non_method_private_callable(tmp_path):
    """A private callable that is NOT a def in the class (instance-attr
    lambda, inherited method) can't be vetted as cross-thread — the
    attribute read itself must be held to the mirror rules."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def __init__(self):
                self._snapshot = lambda: {}

            def stats(self):  # acp: cross-thread
                return self._snapshot()
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "self._snapshot" in violations[0].message


def test_thread_ownership_fires_on_server_scope_engine_reach(tmp_path):
    root = _write(
        tmp_path,
        "server/handlers.py",
        """
        def scrape(engine):
            return len(engine._slots)
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "scrape surface is stats()" in violations[0].message


def test_thread_ownership_fires_on_chained_server_scope_reach(tmp_path):
    """The flight recorder extension: reaching a PRIVATE through a public
    handle rooted at ``engine`` (engine.flight._events) is the same
    ownership break as engine._slots — the recorder's ring buffer is
    engine-written state and server code must use its declared
    cross-thread read methods."""
    root = _write(
        tmp_path,
        "server/handlers.py",
        """
        def scrape(engine):
            raw = engine.flight._events       # chained private reach
            ok = engine.flight.events()       # declared read method: fine
            ok2 = engine.stats()              # public surface: fine
            return len(raw), ok, ok2
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert "_events" in violations[0].message


def test_thread_ownership_fires_on_profiler_scope_server_reach(tmp_path):
    """The compute-observatory extension of the chained-reach rule:
    ``engine.profiler`` is a public handle like ``engine.flight``, but its
    privates (the program table, the goodput ledger) are engine-written
    state — server code must go through the profiler's declared
    cross-thread read methods (``stats()`` / ``ledger()``), never
    ``engine.profiler._programs``."""
    root = _write(
        tmp_path,
        "server/handlers.py",
        """
        def perf(engine):
            raw = engine.profiler._programs    # chained private reach
            led = engine.profiler._goodput     # ledger privates too
            ok = engine.profiler.stats()       # declared read method: fine
            ok2 = engine.profiler.ledger()     # declared read method: fine
            return len(raw), led, ok, ok2
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"] * 2
    messages = " | ".join(v.message for v in violations)
    assert "_programs" in messages and "_goodput" in messages


def test_flight_recorder_cross_thread_reads_lint_clean(tmp_path):
    """The recorder's own posture — reads under its lock from methods
    declared cross-thread — must pass the pass that polices it."""
    root = _write(
        tmp_path,
        "flightish.py",
        """
        import threading

        class Recorder:
            def __init__(self):
                self._lock = threading.Lock()
                self._events = []

            def record(self, kind):
                with self._lock:
                    self._events.append(kind)

            def events(self):  # acp: cross-thread
                with self._lock:
                    return list(self._events)

            def leaky(self):  # acp: cross-thread
                return list(self._events)  # no lock: must fire
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["thread-ownership"]
    assert violations[0].line > 0 and "_events" in violations[0].message


# -- rule: lane-defaults ------------------------------------------------------


def test_lane_defaults_fires_on_missing_and_uninitialized_lanes(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import numpy as np

        class Engine:
            def _verify_dispatch(self, W):  # acp: dispatch-lanes inputs,n_input,starts
                inputs = np.zeros((W, 4), dtype=np.int32)
                n_input = np.empty(W, dtype=np.int32)
                return inputs, n_input
        """,
    )
    violations = analyze([root])
    # np.empty itself + n_input (not ctor-built) + starts (never built)
    assert _rules(violations) == ["lane-defaults"] * 3
    messages = " | ".join(v.message for v in violations)
    assert "np.empty" in messages
    assert "'starts'" in messages and "'n_input'" in messages


def test_lane_defaults_accepts_tuple_assignments(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import numpy as np

        class Engine:
            def _dispatch(self, W):  # acp: dispatch-lanes toks,starts
                toks, starts = np.zeros(W), np.full(W, 64)
                return toks, starts
        """,
    )
    assert analyze([root]) == []


def test_lane_defaults_clean_when_all_lanes_defaulted(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import numpy as np

        class Engine:
            def _verify_dispatch(self, W):  # acp: dispatch-lanes inputs,n_input,starts
                inputs = np.zeros((W, 4), dtype=np.int32)
                n_input = np.zeros(W, dtype=np.int32)
                starts = np.full(W, 64, dtype=np.int32)
                return inputs, n_input, starts
        """,
    )
    assert analyze([root]) == []


# -- rule: jit-purity ---------------------------------------------------------


def test_jit_purity_fires_in_models_scope(tmp_path):
    root = _write(
        tmp_path,
        "models/net.py",
        """
        import time

        def forward(params, x):
            scale = time.monotonic()
            return x * scale
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["jit-purity"]
    assert "time.monotonic" in violations[0].message


def test_path_scoped_rules_bind_on_direct_file_arguments(tmp_path):
    """Linting a single file must keep its directory scope: a models/ file
    passed directly still gets the forward-body blanket."""
    root = _write(
        tmp_path,
        "models/net.py",
        """
        import time

        def forward(params, x):
            return x * time.time()
        """,
    )
    violations = analyze([root / "models" / "net.py"])
    assert _rules(violations) == ["jit-purity"]


def test_jit_purity_fires_on_jitted_functions_anywhere(tmp_path):
    root = _write(
        tmp_path,
        "anywhere.py",
        """
        import jax
        import random

        def impure(x):
            return x + random.random()

        f = jax.jit(impure)
        g = jax.jit(lambda x: x * random.random())
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["jit-purity"] * 2


# -- rule: coord-wallclock ----------------------------------------------------


def test_coord_wallclock_fires_on_unmarked_and_unguarded(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expire(self, deadline):
                return time.monotonic() > deadline

            def _expire_marked(self, deadline):  # acp: leader-local
                now = time.monotonic()
                return now > deadline

            def _expire_good(self, deadline):  # acp: leader-local
                if self._coord_follower:
                    return False
                return time.monotonic() > deadline
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["coord-wallclock"] * 2
    messages = " | ".join(v.message for v in violations)
    assert "not declared" in messages  # _expire: unmarked comparison
    assert "no follower guard" in messages  # _expire_marked: marker is a lie


def test_coord_wallclock_taints_derived_values(tmp_path):
    """'age = now - t0; if age > limit' is still a wall-clock decision —
    taint must propagate through derived assignments, not just the
    direct clock read."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expired(self, started_at, limit):
                now = time.monotonic()
                age = now - started_at
                return age > limit
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["coord-wallclock"]


def test_coord_wallclock_rejects_inverted_guard(tmp_path):
    """``if not self._coord_follower: return`` returns on the LEADER and
    runs the wall-clock decision on every follower — the exact divergence
    the rule exists to stop. It must not satisfy the guard check."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Engine:
            def __init__(self, coordination=None):
                self._coord_follower = coordination is not None

            def _expire(self, deadline):  # acp: leader-local
                if not self._coord_follower:
                    return False
                return time.monotonic() > deadline
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["coord-wallclock"]
    assert "no follower guard" in violations[0].message


def test_coord_wallclock_ignores_uncoordinated_classes(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        import time

        class Plain:
            def expired(self, deadline):
                return time.monotonic() > deadline
        """,
    )
    assert analyze([root]) == []


# -- rule: budget-sharing -----------------------------------------------------


def test_budget_sharing_fires_outside_the_seam(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                budget = sl.sampling.max_tokens - 1
                if len(sl.generated) >= sl.sampling.max_tokens:
                    return 0
                return budget
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["budget-sharing"]
    assert "_verify" in violations[0].message


def test_dispatch_seam_fires_outside_declared_seams(tmp_path):
    """A compiled-program call (or alias) from an unmarked method of a
    seam-declaring class is a new dispatch site: the multi-dispatch
    regression the fused megastep exists to prevent."""
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _megastep_dispatch(self):  # acp: megastep-seam
                return self._jit_megastep(self.params)

            def _sneaky_extra_dispatch(self):
                return self._jit_decode(self.params)

            def _sneaky_alias(self):
                fn = self._jit_prefill
                return fn(self.params)
        """,
    )
    violations = analyze([root])
    assert _rules(violations) == ["dispatch-seam", "dispatch-seam"]
    assert "_sneaky_extra_dispatch" in violations[0].message
    assert "_sneaky_alias" in violations[1].message


def test_dispatch_seam_allows_builder_stores_and_unmarked_classes(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _megastep_dispatch(self):  # acp: megastep-seam
                return self._jit_megastep(self.params)

            def _build_jitted(self):
                # Store context: assignment is construction, not dispatch
                self._jit_megastep = object()

        class NoSeamsDeclared:
            def dispatch(self):
                # a class with no declared seams is out of scope (the rule
                # binds where the megastep contract was adopted)
                return self._jit_anything(self.params)
        """,
    )
    assert analyze([root]) == []


# -- suppression pragma -------------------------------------------------------


def test_inline_pragma_suppresses_a_rule(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                return sl.sampling.max_tokens - 1  # acp-lint: disable=budget-sharing
        """,
    )
    assert analyze([root]) == []


def test_pragma_only_suppresses_the_named_rule(tmp_path):
    root = _write(
        tmp_path,
        "eng.py",
        """
        class Engine:
            def _slot_budget(self, sl):  # acp: budget-seam
                return sl.sampling.max_tokens - len(sl.generated)

            def _verify(self, sl):
                return sl.sampling.max_tokens - 1  # acp-lint: disable=jit-purity
        """,
    )
    assert _rules(analyze([root])) == ["budget-sharing"]


def test_parse_error_is_a_violation_not_a_crash(tmp_path):
    root = _write(tmp_path, "broken.py", "def f(:\n")
    assert _rules(analyze([root])) == ["parse-error"]


# -- metrics-docs drift check -------------------------------------------------


def test_metrics_docs_inventory_in_sync():
    """The shipped tree's gate: every acp_* metric registered in the
    package appears in docs/observability.md and vice versa (the same
    check ``make lint-acp`` runs via --metrics-docs)."""
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    doc = PKG_ROOT.parent / "docs" / "observability.md"
    violations = check_metrics_docs(PKG_ROOT, doc)
    assert not violations, "\n".join(str(v) for v in violations)


def test_metrics_docs_fires_both_drift_directions(tmp_path):
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "from x import REGISTRY\n"
        'REGISTRY.counter_add("acp_documented_total", 1.0)\n'
        'REGISTRY.gauge_set("acp_undocumented_gauge", 2.0)\n'
    )
    doc = tmp_path / "inv.md"
    doc.write_text("- `acp_documented_total` — fine.\n- `acp_ghost_total` — gone.\n")
    rules = sorted(
        (v.rule, "missing" if "missing from" in v.message else "stale")
        for v in check_metrics_docs(pkg, doc)
    )
    assert rules == [("metrics-docs", "missing"), ("metrics-docs", "stale")]


def test_metrics_docs_flags_dynamic_names_and_skips_non_registry(tmp_path):
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "m.py").write_text(
        "from x import REGISTRY\n"
        "name = 'acp_' + kind\n"
        "REGISTRY.counter_add(name, 1.0)\n"      # dynamic: must fire
        "controller.observe(prop, acc)\n"        # not REGISTRY: ignored
    )
    doc = tmp_path / "inv.md"
    doc.write_text("nothing\n")
    violations = check_metrics_docs(pkg, doc)
    assert len(violations) == 1
    assert "non-literal metric name" in violations[0].message


def test_metrics_docs_missing_doc_is_a_violation(tmp_path):
    from agentcontrolplane_tpu.analysis.metrics_docs import check_metrics_docs

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    violations = check_metrics_docs(pkg, tmp_path / "nope.md")
    assert len(violations) == 1 and "does not exist" in violations[0].message


def test_runner_metrics_docs_flag(tmp_path, capsys):
    doc = PKG_ROOT.parent / "docs" / "observability.md"
    assert lint_main([
        "--quiet", "--metrics-docs", str(doc), str(PKG_ROOT / "analysis")
    ]) == 0
    stale = tmp_path / "stale.md"
    stale.write_text("- `acp_engine_never_registered_total`\n")
    assert lint_main([
        "--quiet", "--metrics-docs", str(stale), str(PKG_ROOT / "analysis")
    ]) == 1
    assert "metrics-docs" in capsys.readouterr().out


def test_rule_scoped_run_skips_metrics_docs(tmp_path, capsys):
    """Review fix: --rule scoping must not fail on inventory drift the
    caller didn't ask about."""
    stale = tmp_path / "stale.md"
    stale.write_text("- `acp_engine_never_registered_total`\n")
    assert lint_main([
        "--quiet", "--rule", "jit-purity", "--metrics-docs", str(stale),
        str(PKG_ROOT / "analysis"),
    ]) == 0
