"""analysis/faults_docs.py: the fault-site inventory gate — the shipped
tree must be in sync, and synthetic packages prove both drift
directions, the non-literal-site violation, and the ``_armed.get``
harvest path."""

from __future__ import annotations

from pathlib import Path

import agentcontrolplane_tpu
from agentcontrolplane_tpu.analysis.faults_docs import (
    check_faults_docs,
    code_fault_sites,
    doc_fault_sites,
)

PKG_ROOT = Path(agentcontrolplane_tpu.__file__).parent

FAULTS_DOC = '''"""Switchboard.

- ``engine.crash`` — documented and consumed.
- ``tool.slow`` — documented and consumed via self._faults.
- ``engine.page_pressure`` — consumed via the _armed.get idiom.
"""
'''


def _pkg(tmp_path, faults_doc=FAULTS_DOC, consumer_src=None):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "faults.py").write_text(faults_doc)
    if consumer_src is not None:
        (pkg / "consumer.py").write_text(consumer_src)
    return pkg


def test_shipped_inventory_in_sync():
    """The gate ``make lint-acp`` runs via --faults-docs: every consumed
    site is catalogued in the faults.py docstring and vice versa."""
    violations = check_faults_docs(PKG_ROOT)
    assert not violations, "\n".join(str(v) for v in violations)


def test_shipped_inventory_covers_the_known_sites():
    sites, problems = code_fault_sites(PKG_ROOT)
    assert not problems
    documented = doc_fault_sites(PKG_ROOT / "faults.py")
    for site in ("engine.slow_cycle", "fleet.replica_crash",
                 "engine.page_pressure", "tool.slow"):
        assert site in sites
        assert site in documented


def test_both_drift_directions_fire(tmp_path):
    pkg = _pkg(
        tmp_path,
        consumer_src=(
            "FAULTS.pop('engine.crash')\n"
            "self._faults.pop('tool.slow', match={'name': n})\n"
            "self._armed.get('engine.page_pressure')\n"
            "FAULTS.pop('engine.undocumented_site')\n"  # missing from doc
        ),
    )
    violations = check_faults_docs(pkg)
    msgs = sorted(v.message for v in violations)
    assert len(msgs) == 1  # every documented site consumed; one undocumented
    assert "engine.undocumented_site" in msgs[0]
    assert "missing from" in msgs[0]

    # now drop a consumer: the stale bullet fires the other direction
    (pkg / "consumer.py").write_text("FAULTS.pop('engine.crash')\n")
    violations = check_faults_docs(pkg)
    stale = sorted(v.message for v in violations)
    assert len(stale) == 2
    assert any("engine.page_pressure" in m and "no module consumes" in m
               for m in stale)
    assert any("tool.slow" in m and "no module consumes" in m for m in stale)


def test_non_literal_pop_site_is_a_violation(tmp_path):
    pkg = _pkg(
        tmp_path,
        consumer_src=(
            "FAULTS.pop('engine.crash')\n"
            "self._faults.pop('tool.slow')\n"
            "self._armed.get('engine.page_pressure')\n"
            "site = 'engine.' + kind\n"
            "FAULTS.pop(site)\n"                 # dynamic: must fire
            "other.pop(key)\n"                   # not the injector: skipped
            "self._armed.get(site_var)\n"        # generic get: skipped
        ),
    )
    violations = check_faults_docs(pkg)
    assert len(violations) == 1
    assert "non-literal fault site" in violations[0].message
    assert violations[0].line == 5


def test_missing_faults_py_is_a_violation(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    violations = check_faults_docs(pkg)
    assert len(violations) == 1 and "does not exist" in violations[0].message
