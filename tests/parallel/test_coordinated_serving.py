"""Coordinated multi-host serving (engine/coordination.py).

Two layers of proof:
1. Protocol determinism in ONE process: a follower engine replaying the
   leader's frame stream generates exactly the same tokens (admission is a
   pure function of the replicated request stream).
2. REAL 2-OS-process SPMD: two jax.distributed processes form one global
   tp=4 mesh; rank 0's leader engine and rank 1's follower engine join the
   SAME global dispatches in lockstep, and rank 0's greedy tokens match a
   single-process run of the same global computation.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import subprocess
import sys
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.coordination import (
    CoordinationFollower,
    CoordinationLeader,
    deserialize_request,
    serialize_request,
)
from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

SERVE_WORKER = os.path.join(os.path.dirname(__file__), "mp_serve_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
TINY = dataclasses.replace(PRESETS["tiny"], vocab_size=512)


def _last_json(out: str) -> dict:
    """gloo prints connection banners on stdout (including AFTER our JSON
    when the exit barrier runs); take the last parseable JSON line."""
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise AssertionError(f"no JSON line in worker output: {out[-500:]!r}")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_request_serialization_round_trip():
    from concurrent.futures import Future

    from agentcontrolplane_tpu.engine.engine import _Request

    req = _Request(
        rid="abc123",
        prompt=[1, 2, 3],
        sampling=SamplingParams(
            temperature=0.5, top_k=4, max_tokens=7, json_only=True,
            forced_prefix=(9, 8),
        ),
        future=Future(),
        truncated=True,
    )
    out = deserialize_request(json.loads(json.dumps(serialize_request(req))))
    assert out.rid == req.rid and out.prompt == req.prompt
    assert out.sampling == req.sampling
    assert out.truncated is True


def _engine(mesh, coordination=None):
    return Engine(
        config=TINY,
        tokenizer=ByteTokenizer(),
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        prefix_cache_entries=0,
        seed=0,
        coordination=coordination,
    )


def _self_signed_cert(tmp_path):
    """Self-signed cert+key for the coordination TLS leg (the follower pins
    the same cert as its CA)."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "acp-coord")])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(datetime.datetime.utcnow() - datetime.timedelta(days=1))
        .not_valid_after(datetime.datetime.utcnow() + datetime.timedelta(days=1))
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / "coord.crt"
    key_path = tmp_path / "coord.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


def test_follower_handshake_gates_admission():
    """Only a peer that completes the HELLO (rank + token) counts as a
    follower: a stray TCP connector must neither satisfy
    wait_for_followers nor receive frames, and a wrong token is refused."""
    leader = CoordinationLeader(bind="127.0.0.1:0", token="sekrit",
                               handshake_timeout=3.0)
    host, _, port = leader.address.rpartition(":")
    try:
        stray = socket.create_connection((host, int(port)))
        with pytest.raises(TimeoutError):
            leader.wait_for_followers(1, timeout=1.0)
        stray.close()

        with pytest.raises(ConnectionError):
            CoordinationFollower(
                leader.address, rank=1, token="wrong",
                connect_timeout=5.0, recv_timeout=5.0,
            )

        fol = CoordinationFollower(leader.address, rank=1, token="sekrit")
        leader.wait_for_followers(1, timeout=10.0)
        leader.publish([], ["cancel-1"])
        frame = fol.recv()
        assert frame["seq"] == 0 and frame["cancels"] == ["cancel-1"]
        fol.close()
    finally:
        leader.close()


def test_duplicate_follower_rank_rejected():
    """Two connections claiming one rank means the real rank set is
    incomplete: the duplicate HELLO is refused, wait_for_followers counts
    DISTINCT ranks (lockstep can't be satisfied early by a double-connect),
    and the original connection keeps receiving frames."""
    leader = CoordinationLeader(bind="127.0.0.1:0")
    try:
        first = CoordinationFollower(leader.address, rank=1)
        leader.wait_for_followers(1, timeout=10.0)
        with pytest.raises(ConnectionError):
            CoordinationFollower(
                leader.address, rank=1, connect_timeout=5.0, recv_timeout=5.0
            )
        with pytest.raises(TimeoutError):
            leader.wait_for_followers(2, timeout=0.5)
        second = CoordinationFollower(leader.address, rank=2)
        leader.wait_for_followers(2, timeout=10.0)
        leader.publish([], [])
        assert first.recv()["seq"] == 0
        assert second.recv()["seq"] == 0
        first.close()
        second.close()
    finally:
        leader.close()


def test_coordination_over_tls(tmp_path):
    """The frame channel with the REST surface's encryption posture: TLS +
    token; a plaintext client cannot join a TLS leader."""
    pytest.importorskip("cryptography")  # needed only to mint the test cert
    from agentcontrolplane_tpu.engine.coordination import (
        client_ssl_context,
        server_ssl_context,
    )

    cert, key = _self_signed_cert(tmp_path)
    leader = CoordinationLeader(
        bind="127.0.0.1:0", token="sekrit",
        ssl_context=server_ssl_context(cert, key), handshake_timeout=3.0,
    )
    try:
        fol = CoordinationFollower(
            leader.address, rank=1, token="sekrit",
            ssl_context=client_ssl_context(cert),
        )
        leader.wait_for_followers(1, timeout=10.0)
        leader.publish([], [], hold=True)
        leader.publish([], [], stop=True)
        assert fol.recv()["hold"] is True
        assert fol.recv()["stop"] is True
        fol.close()

        with pytest.raises((ConnectionError, OSError)):
            CoordinationFollower(
                leader.address, rank=1, token="sekrit",
                connect_timeout=5.0, recv_timeout=5.0,
            )
    finally:
        leader.close()


def test_follower_replays_leader_stream_identically():
    """One process, two engines: the follower consumes only the frame
    stream, yet generates the same token count and drains to idle — the
    decisions are fully determined by the frames."""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    leader_chan = CoordinationLeader(bind="127.0.0.1:0")
    leader = _engine(mesh, coordination=leader_chan)
    follower = _engine(mesh, coordination=CoordinationFollower(leader_chan.address))
    leader_chan.wait_for_followers(1, timeout=30.0)
    leader.start()
    follower.start()
    try:
        futs = [
            leader.submit("prompt %d" % i, SamplingParams(temperature=0.0, max_tokens=6))
            for i in range(3)
        ]
        results = [f.result(timeout=300) for f in futs]
        total = sum(len(r.tokens) for r in results)
        assert total > 0
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (
                follower.tokens_generated == leader.tokens_generated
                and follower.stats()["active_slots"] == 0
            ):
                break
            time.sleep(0.05)
        assert follower.tokens_generated == leader.tokens_generated
        assert follower.stats()["waiting"] == 0
    finally:
        leader.stop()  # publishes the stop frame; follower loop ends with it
        follower.stop()
        leader_chan.close()


def test_follower_rejects_local_submissions():
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    leader_chan = CoordinationLeader(bind="127.0.0.1:0")
    follower = _engine(mesh, coordination=CoordinationFollower(leader_chan.address))
    follower.start()
    try:
        fut = follower.submit("nope", SamplingParams(max_tokens=2))
        with pytest.raises(RuntimeError, match="rank 0"):
            fut.result(timeout=10)
    finally:
        follower.stop()
        leader_chan.close()


def _spawn(pid: int, nproc: int, jax_port: int, coord_port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_PLATFORMS", None)  # worker forces cpu via jax.config
    return subprocess.Popen(
        [sys.executable, SERVE_WORKER, str(pid), str(nproc), str(jax_port), str(coord_port)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )


def test_two_process_coordinated_serving_matches_single_process():
    jax_port, coord_port = _free_port(), _free_port()
    procs = [_spawn(i, 2, jax_port, coord_port) for i in range(2)]
    results = []
    for p in procs:
        try:
            results.append(p.communicate(timeout=540))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
    # report BOTH ranks on failure: a gloo abort on one rank is usually
    # the symptom of the OTHER rank dying first
    for i, p in enumerate(procs):
        assert p.returncode == 0, "rank %d failed:\n%s\n--- other rank ---\n%s" % (
            i, results[i][1][-2000:], results[1 - i][1][-2000:]
        )
    outs = [_last_json(out) for out, _ in results]

    assert outs[1] == {"follower": "done"}
    two_proc_tokens = outs[0]["tokens"]
    assert all(len(t) > 0 for t in two_proc_tokens)

    # single-process reference: the same global tp=4 computation, with all
    # 4 virtual devices local to one process
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_PLATFORMS", None)
    ref = subprocess.run(
        [sys.executable, SERVE_WORKER, "0", "1", "0", "0"],
        capture_output=True,
        text=True,
        timeout=540,
        env=env,
    )
    assert ref.returncode == 0, f"reference worker failed:\n{ref.stderr[-3000:]}"
    ref_tokens = _last_json(ref.stdout)["tokens"]
    assert two_proc_tokens == ref_tokens


def test_cancel_lockstep_between_leader_and_follower():
    """A cancelled in-flight request must finish (freeing its slot) at the
    SAME frame on both engines — cancels apply only through the replicated
    frame stream, never from the leader's live set."""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    leader_chan = CoordinationLeader(bind="127.0.0.1:0")
    leader = _engine(mesh, coordination=leader_chan)
    follower = _engine(mesh, coordination=CoordinationFollower(leader_chan.address))
    leader_chan.wait_for_followers(1, timeout=30.0)
    leader.start()
    follower.start()
    try:
        long = leader.submit(
            "cancel me", SamplingParams(temperature=0.0, max_tokens=4096)
        )
        short = leader.submit(
            "finish me", SamplingParams(temperature=0.0, max_tokens=6)
        )
        short.result(timeout=300)
        leader.cancel(long)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            ls, fs = leader.stats(), follower.stats()
            if (
                ls["active_slots"] == 0 and fs["active_slots"] == 0
                and leader.tokens_generated == follower.tokens_generated
            ):
                break
            time.sleep(0.05)
        assert leader.stats()["active_slots"] == 0
        assert follower.stats()["active_slots"] == 0
        assert leader.tokens_generated == follower.tokens_generated
    finally:
        leader.stop()
        follower.stop()
        leader_chan.close()


def test_admission_hold_replicates_through_frames():
    """hold_admission (prewarm batch formation) rides the frame stream:
    followers skip slot-filling the same iterations, then admit the same
    single batch — token counts stay equal."""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    leader_chan = CoordinationLeader(bind="127.0.0.1:0")
    leader = _engine(mesh, coordination=leader_chan)
    follower = _engine(mesh, coordination=CoordinationFollower(leader_chan.address))
    leader_chan.wait_for_followers(1, timeout=30.0)
    leader.start()
    follower.start()
    try:
        with leader.hold_admission():
            futs = [
                leader.submit(
                    "held %d" % i, SamplingParams(temperature=0.0, max_tokens=5)
                )
                for i in range(3)
            ]
            time.sleep(0.5)  # several held frames stream to the follower
            assert leader.stats()["active_slots"] == 0  # nothing admitted yet
        for f in futs:
            f.result(timeout=300)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (
                follower.tokens_generated == leader.tokens_generated
                and follower.stats()["active_slots"] == 0
            ):
                break
            time.sleep(0.05)
        assert follower.tokens_generated == leader.tokens_generated
    finally:
        leader.stop()
        follower.stop()
        leader_chan.close()
