"""REAL multi-process jax.distributed: two OS processes form one global mesh
over gRPC coordination and train in lockstep (VERDICT r1 #6 — exercises
parallel/distributed.py beyond single-process virtual meshes).

The worker (mp_worker.py) joins a 2-process cluster, builds the
dp(across-process) x tp(in-process) mesh, and runs two deterministic train
steps. Assertions: both processes observe identical losses (SPMD — the psum
crossed the process boundary), and those losses match a single-process run
of the same global computation.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(pid: int, nproc: int, port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.pop("JAX_PLATFORMS", None)  # worker forces cpu via jax.config
    return subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(nproc), str(port)],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
        text=True,
    )


def test_two_process_train_step_matches_single_process():
    port = _free_port()
    procs = [_spawn(i, 2, port) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    for o in outs:
        assert o["info"]["process_count"] == 2
        assert o["info"]["global_devices"] == 4
    # SPMD: both processes computed the same global losses
    assert outs[0]["losses"] == pytest.approx(outs[1]["losses"], rel=1e-6)

    # single-process reference: same mesh shape, all 4 devices local
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("JAX_PLATFORMS", None)
    ref = subprocess.run(
        [sys.executable, WORKER, "0", "1", "0"],
        capture_output=True,
        text=True,
        env=env,
        timeout=420,
    )
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = json.loads(ref.stdout.strip().splitlines()[-1])["losses"]
    assert outs[0]["losses"] == pytest.approx(ref_losses, rel=1e-4)
