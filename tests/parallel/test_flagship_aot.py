"""Flagship-geometry AOT sharding validation (VERDICT r2 #7).

The north star serves llama3-8b on a v5e-8: tp=8 (n_kv_heads=8 — exactly one
KV head per chip, the divisibility boundary) or tp=4 with context-parallel
KV over sp=2. Nothing in the single-chip bench or the tiny-config dryrun
exercises those layouts, so a sharding bug (non-divisible dim, spec/pytree
mismatch, uninferable collective) could hide until real v5e-8 hardware.
These tests AOT-lower + GSPMD-compile the real 8B prefill and decode on the
8-device virtual CPU mesh — ShapeDtypeStructs only, no 8B allocation.
"""

from __future__ import annotations

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

needs_8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


@needs_8
def test_llama3_8b_lowers_at_tp8():
    from __graft_entry__ import _aot_flagship_check

    _aot_flagship_check({"tp": 8})


@needs_8
def test_llama3_8b_lowers_at_tp4_sp2():
    from __graft_entry__ import _aot_flagship_check

    _aot_flagship_check({"sp": 2, "tp": 4})
