"""Pipeline parallelism (parallel/pipeline.py): the GPipe schedule over a
'pp' mesh axis must be numerically transparent — logits AND gradients equal
the plain forward — and must communicate only neighbor-sized activations
(no layer-stack gather)."""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.models.llama import PRESETS, forward, init_params
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.parallel.pipeline import (
    pipeline_forward,
    pipeline_loss_fn,
    pipeline_shardings,
)

TINY = dataclasses.replace(PRESETS["tiny"], n_layers=4)


def _setup(mesh):
    params = init_params(TINY, jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, TINY.vocab_size, size=(4, 16)),
        dtype=jnp.int32,
    )
    p_sh = pipeline_shardings(mesh, TINY, params)
    return jax.device_put(params, p_sh), tokens, p_sh


def test_pipeline_forward_matches_plain_forward():
    params = init_params(TINY, jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, TINY.vocab_size, size=(4, 16)),
        dtype=jnp.int32,
    )
    ref = forward(params, tokens, TINY)
    for axes in ({"pp": 4}, {"pp": 2}, {"dp": 2, "pp": 2}):
        n = int(np.prod(list(axes.values())))
        mesh = make_mesh(axes, devices=jax.devices()[:n])
        params_pp, tokens_j, _ = _setup(mesh)
        out = jax.jit(
            lambda p, t, mesh=mesh: pipeline_forward(p, t, TINY, mesh)
        )(params_pp, tokens_j)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
            err_msg=str(axes),
        )


def test_pipeline_gradients_match_plain_gradients():
    """jax.grad through the schedule (ppermute transpose = reverse
    rotation) must equal the unpipelined gradients — the GPipe backward
    emerges from autodiff, not hand-written code."""
    from agentcontrolplane_tpu.train.trainer import lm_loss

    def plain_loss(params, tokens, mask):
        return lm_loss(params, tokens, mask, TINY)

    params = init_params(TINY, jax.random.key(0))
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(1, TINY.vocab_size, size=(4, 12)), dtype=jnp.int32)
    mask = jnp.ones_like(tokens)
    ref_loss, ref_grads = jax.value_and_grad(plain_loss)(params, tokens, mask)

    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    p_sh = pipeline_shardings(mesh, TINY, params)
    params_pp = jax.device_put(params, p_sh)
    loss, grads = jax.jit(
        jax.value_and_grad(
            lambda p, t, m: pipeline_loss_fn(p, t, m, TINY, mesh)
        )
    )(params_pp, tokens, mask)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=2e-5)
    flat_ref = jax.tree_util.tree_leaves(ref_grads)
    flat_pp = jax.tree_util.tree_leaves(grads)
    assert len(flat_ref) == len(flat_pp)
    for a, b in zip(flat_ref, flat_pp):
        np.testing.assert_allclose(
            np.asarray(b, dtype=np.float32), np.asarray(a, dtype=np.float32),
            rtol=5e-3, atol=1e-5,
        )


def test_pipeline_no_layer_stack_gather():
    """The compiled HLO must not all-gather the layer stack: stages
    exchange only [mb, T, D] activations (collective-permute)."""
    import re

    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    params_pp, tokens, _ = _setup(mesh)
    compiled = (
        jax.jit(lambda p, t: pipeline_forward(p, t, TINY, mesh))
        .lower(params_pp, tokens)
        .compile()
    )
    text = compiled.as_text()
    assert "collective-permute" in text  # the rotation is really there
    stack_elems = TINY.n_layers * TINY.dim * TINY.ffn_dim  # largest stacked leaf
    for line in text.splitlines():
        if "all-gather" not in line:
            continue
        dims = re.search(r"\[([0-9,]+)\]", line)
        assert dims is not None, line
        elems = int(np.prod([int(x) for x in dims.group(1).split(",")]))
        assert elems < stack_elems // 2, f"layer-stack all-gather: {line.strip()[:160]}"


def test_pipeline_validates_divisibility():
    mesh = make_mesh({"pp": 4}, devices=jax.devices()[:4])
    cfg = dataclasses.replace(TINY, n_layers=3)
    params = init_params(cfg, jax.random.key(0))
    tokens = jnp.zeros((4, 8), dtype=jnp.int32)
    with pytest.raises(ValueError, match="n_layers"):
        pipeline_forward(params, tokens, cfg, mesh)


def test_trainer_pipeline_parallel_step_matches_plain():
    """Trainer(pipeline_parallel=True) over dp2 x pp2: one train step's
    loss equals the unsharded trainer's (same objective, same init)."""
    import optax

    from agentcontrolplane_tpu.train.trainer import Trainer

    batch = np.random.default_rng(3).integers(1, TINY.vocab_size, size=(4, 16))

    def one_step(mesh_axes, **kw):
        n = int(np.prod(list(mesh_axes.values())))
        mesh = make_mesh(mesh_axes, devices=jax.devices()[:n])
        tr = Trainer(config=TINY, mesh=mesh, optimizer=optax.adamw(1e-3), **kw)
        params, opt = tr.init(jax.random.key(0))
        tokens, mask = tr.shard_batch(batch)
        _, _, loss = tr.train_step(params, opt, tokens, mask)
        return float(loss)

    pp_loss = one_step({"dp": 2, "pp": 2}, pipeline_parallel=True)
    ref_loss = one_step({"dp": 1, "tp": 1})
    assert np.isfinite(pp_loss)
    np.testing.assert_allclose(pp_loss, ref_loss, rtol=2e-3)


# -- gemma-2 soft-caps through the pipelined training path ------------------
# (pure per-stage math — no shard_map, so these run on any device count)

G2ISH = dataclasses.replace(
    PRESETS["tiny"], n_layers=4, attn_logit_softcap=5.0, final_logit_softcap=3.0
)


def test_stage_apply_and_head_match_plain_forward_with_softcaps():
    """The pipeline's per-stage body must thread the attention-logit
    soft-cap and its head must apply the final-logit soft-cap: one stage
    holding ALL layers, composed with the shared embed/norm/head, must
    reproduce the plain forward exactly. Before the fix, _stage_apply
    dropped the attention cap and pipeline_forward skipped the final cap —
    silently training a different model than configured."""
    from agentcontrolplane_tpu.models.llama import _embed, _final_norm_w, _head_logits
    from agentcontrolplane_tpu.ops.norms import rms_norm
    from agentcontrolplane_tpu.parallel.pipeline import _stage_apply

    c = G2ISH
    params = init_params(c, jax.random.key(1))
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(1, c.vocab_size, size=(2, 16)),
        dtype=jnp.int32,
    )
    positions = jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32), (2, 16))
    x = _embed(params, tokens, c)
    x = _stage_apply(params["layers"], x, positions, c)
    x = rms_norm(x, _final_norm_w(params, c), c.norm_eps)
    logits = _head_logits(x, params, c)
    ref = forward(params, tokens, c)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), rtol=2e-4, atol=2e-4)
    # and the caps genuinely bite on this config (the comparison above is
    # not vacuously equal to the uncapped model)
    uncapped = forward(
        params, tokens, dataclasses.replace(c, attn_logit_softcap=0.0, final_logit_softcap=0.0)
    )
    assert not np.allclose(np.asarray(ref), np.asarray(uncapped), rtol=2e-4, atol=2e-4)


def test_forward_refuses_custom_attn_impl_with_softcap():
    """refuse-don't-mis-serve: a swapped-in attention op can't apply the
    configured attention soft-cap, so forward must raise instead of
    silently computing the uncapped model."""
    params = init_params(G2ISH, jax.random.key(0))
    tokens = jnp.zeros((1, 8), dtype=jnp.int32)
    with pytest.raises(ValueError, match="softcap"):
        forward(params, tokens, G2ISH, attn_impl=lambda q, k, v, positions: q)


def test_trainer_refuses_ring_attention_with_softcap():
    import optax

    from agentcontrolplane_tpu.train.trainer import Trainer

    mesh = make_mesh({"sp": 2}, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="soft"):
        Trainer(
            config=G2ISH, mesh=mesh, optimizer=optax.sgd(1e-3),
            sequence_parallel=True,
        )
