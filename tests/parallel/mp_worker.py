"""Worker for the real multi-process jax.distributed test.

Invoked as: python mp_worker.py <process_id> <num_processes> <coordinator_port>

Each process contributes 2 virtual CPU devices; together they form the
dp(across processes) x tp(within process) global mesh and run two identical
train steps on a deterministic batch, printing the losses as JSON.
"""

import json
import os
import sys

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")  # the axon harness overrides the env var
# deterministic compiles across ranks (see mp_serve_worker.py): a cache
# hit on one rank + fresh compile on the other can decompose collectives
# differently and abort gloo mid-run
jax.config.update("jax_enable_compilation_cache", False)

import numpy as np
import optax

from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.distributed import (
    global_mesh,
    initialize_distributed,
    runtime_info,
)
from agentcontrolplane_tpu.train.trainer import Trainer


def main() -> None:
    initialize_distributed(f"localhost:{port}", nproc, pid)
    info = runtime_info()
    mesh = global_mesh({"dp": 2, "tp": 2})

    cfg = PRESETS["tiny"]
    trainer = Trainer(config=cfg, mesh=mesh, optimizer=optax.adam(1e-3))
    params, opt_state = trainer.init(jax.random.key(0))

    # deterministic GLOBAL batch; every process materializes the same array
    # and hands JAX its addressable shards
    rng = np.random.RandomState(7)
    global_tokens = rng.randint(1, cfg.vocab_size, size=(4, 32)).astype(np.int32)
    global_mask = np.ones_like(global_tokens)

    def put(arr):
        return jax.make_array_from_callback(
            arr.shape, trainer.batch_sharding, lambda idx: arr[idx]
        )

    tokens, mask = put(global_tokens), put(global_mask)
    losses = []
    for _ in range(2):
        params, opt_state, loss = trainer.train_step(params, opt_state, tokens, mask)
        losses.append(float(loss))
    print(json.dumps({"losses": losses, "info": info}), flush=True)


if __name__ == "__main__":
    main()
