"""Worker for the real multi-process coordinated SERVING test.

Invoked as:
  python mp_serve_worker.py <pid> <nproc> <jax_port> <coord_port>

Rank 0 runs the leader engine over the 2-process global tp mesh, submits
three greedy prompts, and prints their tokens; rank 1 runs a follower that
replays the broadcast admission frames and joins the same global
dispatches. With nproc=1 it runs the single-process reference (no
coordination, all devices local).
"""

import json
import os
import sys

pid, nproc, jax_port, coord_port = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")  # the axon harness overrides the env var
# The persistent compile cache holds XLA:CPU AOT entries compiled on other
# machines (the cpu_aot_loader machine-feature warnings). If one rank loads
# a cached executable while the other recompiles fresh, their collective
# DECOMPOSITIONS can differ -> gloo "received data size doesn't match"
# aborts mid-run. Multi-process CPU workers must compile deterministically.
jax.config.update("jax_enable_compilation_cache", False)

import dataclasses

from agentcontrolplane_tpu.engine.coordination import (
    CoordinationFollower,
    CoordinationLeader,
)
from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.distributed import global_mesh, initialize_distributed

CFG = dataclasses.replace(
    PRESETS["tiny"], n_heads=4, n_kv_heads=4, vocab_size=512
)
PROMPTS = ["hello world", "bb", "coordinated serving"]


def build_engine(mesh, coordination):
    return Engine(
        config=CFG,
        tokenizer=ByteTokenizer(),
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        prefix_cache_entries=0,
        seed=0,
        coordination=coordination,
    )


def main() -> None:
    if nproc > 1:
        initialize_distributed(f"localhost:{jax_port}", nproc, pid)
    # tp over every global device: 2 procs x 2 local = tp4; the
    # single-process reference runs with 4 local devices = the same tp4
    mesh = global_mesh({"tp": len(jax.devices())})

    if nproc == 1:
        coordination = None
    elif pid == 0:
        coordination = CoordinationLeader(
            bind=f"127.0.0.1:{coord_port}", token="mp-secret"
        )
        coordination.wait_for_followers(nproc - 1, timeout=120.0)
    else:
        coordination = CoordinationFollower(
            f"127.0.0.1:{coord_port}", rank=pid, token="mp-secret"
        )

    engine = build_engine(mesh, coordination)
    engine.start()
    try:
        if pid == 0:
            futs = [
                engine.submit(
                    list(ByteTokenizer().encode(p)),
                    SamplingParams(temperature=0.0, max_tokens=8),
                )
                for p in PROMPTS
            ]
            tokens = [f.result(timeout=300).tokens for f in futs]
            print(json.dumps({"tokens": tokens}), flush=True)
        else:
            # follower: serve until the leader's stop frame ends the loop.
            # NO timeout here: giving up early would stop this engine
            # mid-stream and desynchronize the ranks' dispatch sequences
            # (the leader always publishes stop in its finally; a dead
            # leader surfaces via the recv timeout crashing the loop).
            engine._thread.join()
            print(json.dumps({"follower": "done"}), flush=True)
    finally:
        engine.stop()
        if nproc > 1:
            # exit barrier: a rank tearing its runtime down while the other
            # still has the final decode block's collectives in flight
            # aborts gloo mid-transfer; align both ranks after their engine
            # loops have fully drained before any process exits
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("acp-serve-exit")
            jax.distributed.shutdown()
        if coordination is not None:
            coordination.close()


if __name__ == "__main__":
    main()
