"""Context-parallel serving: the slot KV cache's ctx dim sharded over the
mesh's 'sp' axis (kv_cache_specs). No model-code change — XLA GSPMD turns
the decode/prefill softmax reductions over the sharded dim into per-shard
flash partials merged by [S, H_kv]-sized all-reduces. These tests pin
(a) numerics vs the replicated cache, (b) the compiled HLO containing NO
all-gather (the failure mode where GSPMD materializes the cache on every
rank), and (c) the full Engine producing identical greedy generations on
an sp x tp mesh vs tp-only.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import (
    PRESETS,
    decode_step,
    init_kv_cache,
    init_params,
)
from agentcontrolplane_tpu.parallel.mesh import (
    kv_cache_shardings,
    make_mesh,
    param_shardings,
)

TINY = dataclasses.replace(PRESETS["tiny"], max_seq_len=256)


def test_decode_step_ctx_sharded_matches_replicated_and_no_allgather():
    cfg = TINY
    S, C = 8, 256
    mesh = make_mesh({"sp": 4, "tp": 2})
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    shape = init_kv_cache(cfg, S, C)["k"].shape
    cache = {
        "k": jnp.asarray(rng.normal(size=shape), dtype=cfg.dtype),
        "v": jnp.asarray(rng.normal(size=shape), dtype=cfg.dtype),
    }
    tokens = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(S,)), dtype=jnp.int32)
    seq_lens = jnp.asarray(rng.integers(1, C - 1, size=(S,)), dtype=jnp.int32)

    ref_cache, ref_logits = jax.jit(
        lambda p, c, t, s: decode_step(p, c, t, s, cfg)
    )(params, cache, tokens, seq_lens)

    cp_shard = kv_cache_shardings(mesh)
    assert cp_shard["k"].spec == P(None, None, "sp", "tp", None)
    p_shard = param_shardings(mesh, cfg, params)
    rep = NamedSharding(mesh, P())
    step = jax.jit(
        lambda p, c, t, s: decode_step(p, c, t, s, cfg),
        in_shardings=(p_shard, cp_shard, rep, rep),
        out_shardings=(cp_shard, rep),
    )
    params_cp = jax.device_put(params, p_shard)
    cache_cp = {k: jax.device_put(cache[k], cp_shard[k]) for k in cache}
    compiled = step.lower(params_cp, cache_cp, tokens, seq_lens).compile()
    out_cache, out_logits = step(params_cp, cache_cp, tokens, seq_lens)

    np.testing.assert_allclose(
        np.asarray(out_logits), np.asarray(ref_logits), rtol=2e-2, atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(out_cache["k"], dtype=np.float32),
        np.asarray(ref_cache["k"], dtype=np.float32),
        rtol=1e-3, atol=1e-4,
    )
    # the whole point: the sharded-softmax merge, not a cache all-gather.
    # The only acceptable gather is the [S, vocab] logits replication at
    # the root (out_shardings=replicated) — tiny. Anything within an order
    # of magnitude of the cache means GSPMD materialized it on every rank.
    import re

    cache_elems = int(np.prod(shape))
    for line in compiled.as_text().splitlines():
        if "all-gather" not in line:
            continue
        dims = re.search(r"\[([0-9,]+)\]", line)
        assert dims is not None, line
        elems = int(np.prod([int(x) for x in dims.group(1).split(",")]))
        assert elems < cache_elems // 16, f"cache-sized all-gather: {line.strip()[:160]}"


def _greedy_workload(eng: Engine) -> list[list[int]]:
    eng.start()
    try:
        futs = [
            eng.submit(
                [1 + i] * (24 + 5 * i),
                SamplingParams(temperature=0.0, max_tokens=16 + 2 * i),
            )
            for i in range(4)
        ]
        first = [f.result(timeout=300).tokens for f in futs]
        # second turn: extended prompts re-enter through the prefix cache /
        # continuation prefill against the sharded cache
        futs = [
            eng.submit(
                [1 + i] * (24 + 5 * i) + first[i][:4] + [2],
                SamplingParams(temperature=0.0, max_tokens=8),
            )
            for i in range(4)
        ]
        return first + [f.result(timeout=300).tokens for f in futs]
    finally:
        eng.stop()


def test_engine_sp_mesh_matches_tp_only():
    def build(mesh):
        return Engine(
            config=TINY,
            tokenizer=ByteTokenizer(),
            max_slots=4,
            max_ctx=256,
            prefill_buckets=(32, 64),
            decode_block_size=4,
            seed=0,
            mesh=mesh,
        )

    ref = _greedy_workload(build(make_mesh({"tp": 2}, devices=jax.devices()[:2])))
    cp = _greedy_workload(build(make_mesh({"sp": 4, "tp": 2})))
    assert cp == ref
    assert all(len(t) > 0 for t in ref)


def test_engine_rejects_bad_cp_configs():
    with pytest.raises(ValueError, match="context-parallel paged"):
        # sp must divide the page size (each rank holds a page slice)
        Engine(
            config=TINY, tokenizer=ByteTokenizer(), max_slots=2, max_ctx=256,
            kv_layout="paged", page_size=2, mesh=make_mesh({"sp": 4, "tp": 2}),
        )
    with pytest.raises(ValueError, match="divisible"):
        Engine(
            config=TINY, tokenizer=ByteTokenizer(), max_slots=2, max_ctx=254,
            mesh=make_mesh({"sp": 4, "tp": 2}),
        )


# -- paged + context parallelism (VERDICT r3 weak #4) ------------------------


def test_decode_step_paged_sp_sharded_matches_replicated_and_no_allgather():
    """The paged pools shard their WITHIN-PAGE dim over sp; decode must
    (a) match the replicated result and (b) compile with no pool-sized
    all-gather — prefix-page sharing composes with long-context sharding."""
    from agentcontrolplane_tpu.models.llama import decode_step_paged, init_paged_cache
    from agentcontrolplane_tpu.ops.paged import TRASH_PAGE

    cfg = TINY
    S, page_size, num_pages = 4, 16, 33
    max_pages = 256 // page_size
    mesh = make_mesh({"sp": 4, "tp": 2})
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    shape = init_paged_cache(cfg, num_pages, page_size)["k"].shape
    pages = {
        "k": jnp.asarray(rng.normal(size=shape), dtype=cfg.dtype),
        "v": jnp.asarray(rng.normal(size=shape), dtype=cfg.dtype),
    }
    tables = np.full((S, max_pages), TRASH_PAGE, dtype=np.int32)
    seq_lens = np.asarray([30, 7, 64, 45], dtype=np.int32)
    nxt = 1
    for s in range(S):
        for i in range(-(-int(seq_lens[s] + 1) // page_size)):
            tables[s, i] = nxt
            nxt += 1
    tokens = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(S,)), dtype=jnp.int32)
    tables = jnp.asarray(tables)
    seq_lens_j = jnp.asarray(seq_lens)
    active = jnp.ones((S,), dtype=bool)

    fn = lambda p, pg, t, s, bt, a: decode_step_paged(p, pg, t, s, bt, a, cfg)
    ref_pages, ref_logits = jax.jit(fn)(
        params, pages, tokens, seq_lens_j, tables, active
    )

    page_spec = NamedSharding(mesh, P(None, None, "sp", "tp", None))
    pg_shard = {"k": page_spec, "v": page_spec}
    p_shard = param_shardings(mesh, cfg, params)
    rep = NamedSharding(mesh, P())
    step = jax.jit(
        fn,
        in_shardings=(p_shard, pg_shard, rep, rep, rep, rep),
        out_shardings=(pg_shard, rep),
    )
    pages_cp = {k: jax.device_put(pages[k], page_spec) for k in pages}
    params_cp = jax.device_put(params, p_shard)
    compiled = step.lower(
        params_cp, pages_cp, tokens, seq_lens_j, tables, active
    ).compile()
    out_pages, out_logits = step(params_cp, pages_cp, tokens, seq_lens_j, tables, active)

    np.testing.assert_allclose(
        np.asarray(out_logits), np.asarray(ref_logits), rtol=2e-2, atol=2e-2
    )
    np.testing.assert_allclose(
        np.asarray(out_pages["k"], dtype=np.float32),
        np.asarray(ref_pages["k"], dtype=np.float32),
        rtol=1e-3, atol=1e-4,
    )
    import re

    pool_elems = int(np.prod(shape))
    for line in compiled.as_text().splitlines():
        if "all-gather" not in line:
            continue
        dims = re.search(r"\[([0-9,]+)\]", line)
        assert dims is not None, line
        elems = int(np.prod([int(x) for x in dims.group(1).split(",")]))
        assert elems < pool_elems // 16, f"pool-sized all-gather: {line.strip()[:160]}"


def test_engine_paged_sp_mesh_matches_tp_only():
    """Full engine on an sp x tp mesh with PAGED KV (prefix cache on):
    greedy generations identical to the tp-only paged engine — including
    second-turn prompts that re-enter through shared prefix pages."""

    def build(mesh):
        return Engine(
            config=TINY,
            tokenizer=ByteTokenizer(),
            max_slots=4,
            max_ctx=256,
            prefill_buckets=(32, 64),
            decode_block_size=4,
            kv_layout="paged",
            page_size=16,
            seed=0,
            mesh=mesh,
        )

    ref = _greedy_workload(build(make_mesh({"tp": 2}, devices=jax.devices()[:2])))
    cp = _greedy_workload(build(make_mesh({"sp": 4, "tp": 2})))
    assert cp == ref
    assert all(len(t) > 0 for t in ref)
