"""docs/crd-reference.md is GENERATED from the pydantic models; this test
keeps it in lockstep with the code (regenerate with
``python scripts/gen_crd_reference.py > docs/crd-reference.md``)."""

from __future__ import annotations

import io
import pathlib
import sys
from contextlib import redirect_stdout

REPO = pathlib.Path(__file__).parent.parent


def test_crd_reference_matches_models():
    sys.path.insert(0, str(REPO / "scripts"))
    import gen_crd_reference

    buf = io.StringIO()
    with redirect_stdout(buf):
        gen_crd_reference.main()
    expected = buf.getvalue()
    actual = (REPO / "docs" / "crd-reference.md").read_text()
    assert actual == expected, (
        "docs/crd-reference.md is stale — regenerate with "
        "`python scripts/gen_crd_reference.py > docs/crd-reference.md`"
    )
