"""Test harness.

- Forces JAX onto a virtual 8-device CPU mesh (multi-chip sharding tests run
  without TPU hardware, per the driver contract).
- Native asyncio test support (async def tests run via asyncio.run).
- Shared builder fixtures live in agentcontrolplane_tpu.testing (shipped in
  the package so bench.py runs without tests/); tests/fixtures.py re-exports.
"""

import asyncio
import inspect
import os

# Must be set before the jax backend initializes. NOTE: this environment's
# axon harness overrides the JAX_PLATFORMS env var, so we must force the
# platform through jax.config (which wins) — see .claude/skills/verify.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

if not os.environ.get("ACP_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture
def store():
    from agentcontrolplane_tpu.kernel import Store

    return Store()
