"""MCP manager tests against a real stdio subprocess (echo_server.py fixture).

Covers the reference's mcpmanager behaviors: connect + handshake + tool
discovery, tool invocation with text flattening, Secret-resolved env vars
(envvar_test.go equivalent), error propagation, reconnect after death.
"""

import json
import os
import sys

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    EnvVar,
    MCPServer,
    MCPServerSpec,
    Secret,
    SecretKeyRef,
    SecretSpec,
)
from agentcontrolplane_tpu.mcp import MCPError, MCPManager, flatten_tool_result
from agentcontrolplane_tpu.mcp.adapters import (
    convert_mcp_tools,
    parse_tool_arguments,
    split_tool_name,
)

SERVER = os.path.join(os.path.dirname(__file__), "echo_server.py")


def echo_server_spec(name="echo", env=None):
    return MCPServer(
        metadata=ObjectMeta(name=name),
        spec=MCPServerSpec(
            transport="stdio",
            command=sys.executable,
            args=[SERVER],
            env=env or [],
        ),
    )


async def test_connect_discovers_tools(store):
    mgr = MCPManager(store)
    try:
        conn = await mgr.connect_server(echo_server_spec())
        assert {t.name for t in conn.tools} == {"echo", "env", "fail", "sleep"}
        assert conn.client.server_info["name"] == "echo-test-server"
        assert mgr.get_tools("echo")  # pool populated
    finally:
        await mgr.close()


async def test_call_tool_flattens_text(store):
    mgr = MCPManager(store)
    try:
        await mgr.connect_server(echo_server_spec())
        result = await mgr.call_tool("echo", "echo", {"message": "hello mcp"})
        assert result == "echo: hello mcp"
    finally:
        await mgr.close()


async def test_secret_env_vars_reach_subprocess(store):
    store.create(
        Secret(
            metadata=ObjectMeta(name="mcp-creds"),
            spec=SecretSpec(data={"token": "s3cr3t-value"}),
        )
    )
    mgr = MCPManager(store)
    try:
        await mgr.connect_server(
            echo_server_spec(
                env=[
                    EnvVar(name="PLAIN", value="plain-value"),
                    EnvVar(name="FROM_SECRET", value_from=SecretKeyRef(name="mcp-creds", key="token")),
                ]
            )
        )
        assert await mgr.call_tool("echo", "env", {"name": "PLAIN"}) == "plain-value"
        assert await mgr.call_tool("echo", "env", {"name": "FROM_SECRET"}) == "s3cr3t-value"
    finally:
        await mgr.close()


async def test_tool_error_raises(store):
    mgr = MCPManager(store)
    try:
        await mgr.connect_server(echo_server_spec())
        with pytest.raises(MCPError, match="scripted failure"):
            await mgr.call_tool("echo", "fail", {})
    finally:
        await mgr.close()


async def test_call_unconnected_server_raises(store):
    mgr = MCPManager(store)
    with pytest.raises(MCPError, match="not connected"):
        await mgr.call_tool("ghost", "tool", {})


async def test_reconnect_replaces_pool_entry(store):
    mgr = MCPManager(store)
    try:
        conn1 = await mgr.connect_server(echo_server_spec())
        conn2 = await mgr.connect_server(echo_server_spec())
        assert mgr.get_connection("echo") is conn2
        assert not conn1.client.alive  # old client closed
        assert await mgr.call_tool("echo", "echo", {"message": "x"}) == "echo: x"
    finally:
        await mgr.close()


def test_adapter_name_mangling():
    from agentcontrolplane_tpu.api.resources import MCPTool

    tools = convert_mcp_tools(
        [MCPTool(name="fetch", description="d", input_schema={"type": "object"})], "web"
    )
    assert tools[0].function.name == "web__fetch"
    assert tools[0].acp_tool_type == "MCP"
    assert split_tool_name("web__fetch") == ("web", "fetch")
    assert split_tool_name("web__fetch__deep") == ("web", "fetch__deep")
    with pytest.raises(ValueError):
        split_tool_name("bare")


def test_parse_tool_arguments():
    assert parse_tool_arguments('{"a": 1}') == {"a": 1}
    assert parse_tool_arguments("") == {}
    with pytest.raises(ValueError):
        parse_tool_arguments("[1,2]")
    with pytest.raises(ValueError):
        parse_tool_arguments("{broken")


def test_flatten_mixed_content():
    out = flatten_tool_result(
        {
            "content": [
                {"type": "text", "text": "line1"},
                {"type": "image", "data": "abc"},
                {"type": "text", "text": "line2"},
            ]
        }
    )
    assert out == 'line1\n{"type": "image", "data": "abc"}\nline2'


def test_parse_quantity():
    from agentcontrolplane_tpu.mcp.stdio import parse_quantity

    assert parse_quantity("512Mi") == 512 * 1024**2
    assert parse_quantity("1Gi") == 1024**3
    assert parse_quantity("100M") == 100_000_000
    assert parse_quantity("2048") == 2048
    assert parse_quantity("1.5Gi") == int(1.5 * 1024**3)


async def test_stdio_memory_limit_applied(store):
    """spec.resources.limits.memory (mcpserver_types.go:30-39) maps to
    RLIMIT_AS on the stdio subprocess: a generous limit still lets the
    server run; the client records the parsed byte count."""
    from agentcontrolplane_tpu.api.resources import ResourceRequirements

    spec = echo_server_spec(name="limited")
    spec.spec.resources = ResourceRequirements(limits={"memory": "1Gi"})
    mgr = MCPManager(store)
    try:
        conn = await mgr.connect_server(spec)
        assert conn.client.memory_limit == 1024**3
        out = await mgr.call_tool("limited", "echo", {"message": "hi"})
        assert "hi" in out
    finally:
        await mgr.close()


async def test_http_transport_against_live_server(store):
    """Streamable-HTTP MCP transport (mcpmanager.go:148 parity) against a
    live aiohttp server: JSON responses, SSE responses, session ids,
    JSON-RPC errors."""
    from aiohttp import web

    calls: list[dict] = []

    async def mcp(request: web.Request) -> web.Response:
        msg = json.loads(await request.read())
        calls.append(msg)
        method = msg.get("method")
        rid = msg.get("id")
        if method == "initialize":
            result = {
                "protocolVersion": "2024-11-05",
                "capabilities": {"tools": {}},
                "serverInfo": {"name": "http-test-server", "version": "1.0"},
            }
            return web.json_response(
                {"jsonrpc": "2.0", "id": rid, "result": result},
                headers={"Mcp-Session-Id": "sess-42"},
            )
        if rid is None:  # notification
            return web.Response(status=202)
        assert request.headers.get("Mcp-Session-Id") == "sess-42"
        if method == "tools/list":
            # SSE-framed response exercises the event-stream parse path
            result = {"tools": [{"name": "greet", "description": "", "inputSchema": {}}]}
            body = f'data: {json.dumps({"jsonrpc": "2.0", "id": rid, "result": result})}\n\n'
            return web.Response(text=body, content_type="text/event-stream")
        if method == "tools/call":
            name = msg["params"]["name"]
            if name == "boom":
                return web.json_response(
                    {"jsonrpc": "2.0", "id": rid,
                     "error": {"code": -32000, "message": "scripted"}}
                )
            text = f"hello {msg['params'].get('arguments', {}).get('who', '')}"
            return web.json_response(
                {"jsonrpc": "2.0", "id": rid,
                 "result": {"content": [{"type": "text", "text": text}]}}
            )
        return web.json_response({"jsonrpc": "2.0", "id": rid, "result": {}})

    app = web.Application()
    app.router.add_post("/mcp", mcp)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]

    mgr = MCPManager(store)
    try:
        server = MCPServer(
            metadata=ObjectMeta(name="httpd"),
            spec=MCPServerSpec(transport="http", url=f"http://127.0.0.1:{port}/mcp"),
        )
        conn = await mgr.connect_server(server)
        assert conn.client.server_info["name"] == "http-test-server"
        assert [t.name for t in conn.tools] == ["greet"]
        out = await mgr.call_tool("httpd", "greet", {"who": "world"})
        assert out == "hello world"
        try:
            await mgr.call_tool("httpd", "boom", {})
            raise AssertionError("expected MCPError")
        except MCPError as e:
            assert "scripted" in str(e)
        assert any(c.get("method") == "notifications/initialized" for c in calls)
    finally:
        await mgr.close()
        await runner.cleanup()


async def test_concurrent_calls_to_one_stdio_server_overlap(store):
    """Overlapped tool execution, transport half: two slow calls to ONE
    stdio server must run concurrently (id-multiplexed reader), not
    serialize behind a request-response lock — and out-of-order responses
    route to the right caller."""
    import asyncio
    import time

    mgr = MCPManager(store)
    try:
        await mgr.connect_server(echo_server_spec())
        t0 = time.monotonic()
        slow, fast, echoed = await asyncio.gather(
            mgr.call_tool("echo", "sleep", {"seconds": 0.8}),
            mgr.call_tool("echo", "sleep", {"seconds": 0.1}),
            mgr.call_tool("echo", "echo", {"message": "while sleeping"}),
        )
        elapsed = time.monotonic() - t0
        assert slow == "slept 0.8" and fast == "slept 0.1"
        assert echoed == "echo: while sleeping"
        # serial execution would take >= 0.9s; overlapped ~0.8s. Generous
        # margin for slow CI, still far below the serial floor.
        assert elapsed < 1.4, elapsed
    finally:
        await mgr.close()
