"""Minimal MCP stdio server used as a test fixture (the reference tests
against real MCP servers like mcp-server-fetch; we need zero-dependency).

Speaks newline-delimited JSON-RPC 2.0: initialize, tools/list, tools/call.
Tools: echo (returns its input), env (returns an env var — used to test
Secret-resolved env injection), fail (returns isError), sleep (responds
after N seconds FROM A THREAD — concurrent sleeps overlap and responses
can arrive out of order, exercising the client's id-multiplexed reader).
"""

import json
import os
import sys
import threading

TOOLS = [
    {
        "name": "echo",
        "description": "echo back the message",
        "inputSchema": {
            "type": "object",
            "properties": {"message": {"type": "string"}},
            "required": ["message"],
        },
    },
    {
        "name": "env",
        "description": "read an environment variable",
        "inputSchema": {
            "type": "object",
            "properties": {"name": {"type": "string"}},
            "required": ["name"],
        },
    },
    {
        "name": "fail",
        "description": "always fails",
        "inputSchema": {"type": "object", "properties": {}},
    },
    {
        "name": "sleep",
        "description": "respond after N seconds (from a worker thread)",
        "inputSchema": {
            "type": "object",
            "properties": {"seconds": {"type": "number"}},
        },
    },
]

_WRITE_LOCK = threading.Lock()


def _write(resp):
    with _WRITE_LOCK:
        sys.stdout.write(json.dumps(resp) + "\n")
        sys.stdout.flush()


def _sleep_worker(msg_id, seconds):
    import time

    time.sleep(seconds)
    _write({
        "jsonrpc": "2.0",
        "id": msg_id,
        "result": {"content": [{"type": "text", "text": f"slept {seconds}"}]},
    })


def handle(msg):
    method = msg.get("method")
    if method == "initialize":
        return {
            "protocolVersion": msg["params"].get("protocolVersion", "2024-11-05"),
            "capabilities": {"tools": {}},
            "serverInfo": {"name": "echo-test-server", "version": "1.0"},
        }
    if method == "tools/list":
        return {"tools": TOOLS}
    if method == "tools/call":
        name = msg["params"]["name"]
        args = msg["params"].get("arguments") or {}
        if name == "echo":
            return {"content": [{"type": "text", "text": f"echo: {args.get('message', '')}"}]}
        if name == "env":
            return {"content": [{"type": "text", "text": os.environ.get(args.get("name", ""), "")}]}
        if name == "fail":
            return {"isError": True, "content": [{"type": "text", "text": "scripted failure"}]}
        return {"isError": True, "content": [{"type": "text", "text": f"unknown tool {name}"}]}
    return None


def main():
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "id" not in msg:
            continue  # notification
        if (
            msg.get("method") == "tools/call"
            and (msg.get("params") or {}).get("name") == "sleep"
        ):
            secs = float((msg["params"].get("arguments") or {}).get("seconds", 0.1))
            threading.Thread(
                target=_sleep_worker, args=(msg["id"], secs), daemon=True
            ).start()
            continue
        result = handle(msg)
        if result is None:
            resp = {
                "jsonrpc": "2.0",
                "id": msg["id"],
                "error": {"code": -32601, "message": f"unknown method {msg.get('method')}"},
            }
        else:
            resp = {"jsonrpc": "2.0", "id": msg["id"], "result": result}
        _write(resp)


if __name__ == "__main__":
    main()
