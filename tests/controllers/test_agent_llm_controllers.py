"""Agent / LLM / ContactChannel / MCPServer controller tests."""

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LLM,
    BaseConfig,
    LLMSpec,
    MCPServer,
    MCPServerSpec,
    SecretKeyRef,
)
from agentcontrolplane_tpu.controllers.agent import AgentReconciler
from agentcontrolplane_tpu.controllers.contactchannel import ContactChannelReconciler
from agentcontrolplane_tpu.controllers.llm import LLMReconciler
from agentcontrolplane_tpu.controllers.mcpserver import MCPServerReconciler
from agentcontrolplane_tpu.humanlayer import LocalHumanLayerClientFactory
from agentcontrolplane_tpu.kernel import EventRecorder
from agentcontrolplane_tpu.llmclient import (
    LLMRequestError,
    MockLLMClient,
    MockLLMClientFactory,
    assistant,
)

from ..fixtures import (
    make_agent,
    make_contactchannel,
    make_llm,
    make_mcpserver,
    make_secret,
    make_task,
)


async def test_agent_all_deps_ready(store):
    recorder = EventRecorder(store)
    rec = AgentReconciler(store, recorder)
    make_llm(store)
    make_mcpserver(store, "fetch", tools=("fetch", "post"))
    make_secret(store)
    make_contactchannel(store, "oncall")
    make_agent(store, name="sub", ready=True)
    agent = make_agent(
        store,
        name="main-agent",
        mcp_servers=["fetch"],
        channels=["oncall"],
        sub_agents=["sub"],
        ready=False,
    )
    result = await rec.reconcile(("Agent", "default", "main-agent"))
    agent = store.get("Agent", "main-agent")
    assert agent.status.ready
    assert agent.status.status == "Ready"
    assert agent.status.valid_mcp_servers[0].name == "fetch"
    assert agent.status.valid_mcp_servers[0].tools == ["fetch", "post"]
    assert agent.status.valid_human_contact_channels == ["oncall"]
    assert [s.name for s in agent.status.valid_sub_agents] == ["sub"]
    # Ready agents are revalidated periodically (dependency drift detection)
    assert result.requeue_after == rec.revalidate_interval


async def test_agent_missing_llm_is_error(store):
    rec = AgentReconciler(store, EventRecorder(store))
    make_agent(store, name="a", llm="nope", ready=False)
    result = await rec.reconcile(("Agent", "default", "a"))
    agent = store.get("Agent", "a")
    assert not agent.status.ready
    assert agent.status.status == "Error"
    assert 'LLM "nope" not found' in agent.status.status_detail
    assert result.requeue_after == rec.requeue_delay


async def test_agent_pending_llm_is_pending(store):
    rec = AgentReconciler(store, EventRecorder(store))
    make_llm(store, ready=False)
    make_agent(store, name="a", ready=False)
    await rec.reconcile(("Agent", "default", "a"))
    agent = store.get("Agent", "a")
    assert agent.status.status == "Pending"


async def test_llm_controller_probe_success(store):
    mock = MockLLMClient(script=[assistant("ok")])
    factory = MockLLMClientFactory(mock)
    rec = LLMReconciler(store, EventRecorder(store), factory, probe=True)
    make_secret(store)
    store.create(
        LLM(
            metadata=ObjectMeta(name="gpt"),
            spec=LLMSpec(
                provider="openai",
                api_key_from=SecretKeyRef(name="test-secret", key="api-key"),
                parameters=BaseConfig(model="gpt-4o"),
            ),
        )
    )
    await rec.reconcile(("LLM", "default", "gpt"))
    llm = store.get("LLM", "gpt")
    assert llm.status.ready and llm.status.status == "Ready"
    # the probe used max_tokens=1 (reference llm/state_machine.go:391-402)
    assert factory.calls[0].spec.parameters.max_tokens == 1


async def test_llm_controller_probe_failure(store):
    mock = MockLLMClient(script=[LLMRequestError(401, "invalid key")])
    rec = LLMReconciler(store, EventRecorder(store), MockLLMClientFactory(mock), probe=True)
    make_secret(store)
    store.create(
        LLM(
            metadata=ObjectMeta(name="gpt"),
            spec=LLMSpec(
                provider="openai",
                api_key_from=SecretKeyRef(name="test-secret", key="api-key"),
            ),
        )
    )
    result = await rec.reconcile(("LLM", "default", "gpt"))
    llm = store.get("LLM", "gpt")
    assert not llm.status.ready
    assert llm.status.status == "Error"
    assert "invalid key" in llm.status.status_detail
    assert result.requeue_after == 30.0


async def test_llm_controller_missing_secret(store):
    rec = LLMReconciler(store, EventRecorder(store), MockLLMClientFactory(MockLLMClient()), probe=False)
    store.create(
        LLM(
            metadata=ObjectMeta(name="gpt"),
            spec=LLMSpec(
                provider="openai",
                api_key_from=SecretKeyRef(name="absent", key="api-key"),
            ),
        )
    )
    await rec.reconcile(("LLM", "default", "gpt"))
    llm = store.get("LLM", "gpt")
    assert llm.status.status == "Error"
    assert 'secret "absent" not found' in llm.status.status_detail


async def test_contactchannel_validation(store):
    rec = ContactChannelReconciler(
        store, EventRecorder(store), LocalHumanLayerClientFactory(), verify_credentials=True
    )
    make_secret(store)
    make_contactchannel(store, "oncall", ready=False)
    await rec.reconcile(("ContactChannel", "default", "oncall"))
    ch = store.get("ContactChannel", "oncall")
    assert ch.status.ready and ch.status.status == "Ready"


async def test_contactchannel_bad_email(store):
    rec = ContactChannelReconciler(store, EventRecorder(store), None, verify_credentials=False)
    make_secret(store)
    ch = make_contactchannel(store, "bad", ready=False)
    ch = store.get("ContactChannel", "bad")
    ch.spec.email.address = "not-an-email"
    store.update(ch)
    await rec.reconcile(("ContactChannel", "default", "bad"))
    ch = store.get("ContactChannel", "bad")
    assert ch.status.status == "Error"
    assert "invalid email" in ch.status.status_detail


class StubMCPManager:
    """Scriptable MCPManager for the controller test."""

    def __init__(self, fail=False):
        self.fail = fail
        self.connected = {}

    async def connect_server(self, server):
        if self.fail:
            raise RuntimeError("spawn failed")
        from agentcontrolplane_tpu.api.resources import MCPTool
        from agentcontrolplane_tpu.mcp.manager import MCPConnection

        class _Client:
            alive = True

        conn = MCPConnection(
            name=server.metadata.name,
            client=_Client(),
            tools=[MCPTool(name="fetch", description="fetch a url")],
        )
        self.connected[server.metadata.name] = conn
        return conn

    def get_connection(self, name):
        return self.connected.get(name)

    async def disconnect_server(self, name):
        self.connected.pop(name, None)


async def test_mcpserver_connects_and_discovers_tools(store):
    rec = MCPServerReconciler(store, EventRecorder(store), StubMCPManager())
    store.create(
        MCPServer(
            metadata=ObjectMeta(name="fetch"),
            spec=MCPServerSpec(transport="stdio", command="uvx", args=["mcp-server-fetch"]),
        )
    )
    result = await rec.reconcile(("MCPServer", "default", "fetch"))
    server = store.get("MCPServer", "fetch")
    assert server.status.connected
    assert [t.name for t in server.status.tools] == ["fetch"]
    assert result.requeue_after == rec.keepalive_interval


async def test_mcpserver_connect_failure_retries(store):
    rec = MCPServerReconciler(store, EventRecorder(store), StubMCPManager(fail=True))
    store.create(
        MCPServer(
            metadata=ObjectMeta(name="fetch"),
            spec=MCPServerSpec(transport="stdio", command="nope"),
        )
    )
    result = await rec.reconcile(("MCPServer", "default", "fetch"))
    server = store.get("MCPServer", "fetch")
    assert not server.status.connected
    assert server.status.status == "Error"
    assert result.requeue_after == 30.0


async def test_mcpserver_invalid_spec_terminal(store):
    rec = MCPServerReconciler(store, EventRecorder(store), StubMCPManager())
    store.create(
        MCPServer(metadata=ObjectMeta(name="bad"), spec=MCPServerSpec(transport="stdio"))
    )
    result = await rec.reconcile(("MCPServer", "default", "bad"))
    server = store.get("MCPServer", "bad")
    assert server.status.status == "Error"
    assert "requires a command" in server.status.status_detail
    assert result.requeue_after is None


async def test_llm_controller_tpu_mesh_mismatch_is_invalid(store):
    """A provider:tpu LLM declaring tensorParallelism/contextParallelism
    that disagrees with the live engine's mesh must fail validation — the
    fields are declarative intent, not silent no-ops."""
    from agentcontrolplane_tpu.api.resources import TPUProviderConfig

    class FakeEngine:
        quantize = None
        quantize_kv = False

        class mesh:
            shape = {"sp": 1, "tp": 2}

    class FakeFactory:
        engine = FakeEngine()

    rec = LLMReconciler(store, EventRecorder(store), FakeFactory(), probe=False)
    store.create(
        LLM(
            metadata=ObjectMeta(name="tpu-bad"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="bench-1b"),
                tpu=TPUProviderConfig(preset="bench-1b", context_parallelism=4),
            ),
        )
    )
    await rec.reconcile(("LLM", "default", "tpu-bad"))
    llm = store.get("LLM", "tpu-bad")
    assert not llm.status.ready
    assert "contextParallelism" in llm.status.status_detail

    store.create(
        LLM(
            metadata=ObjectMeta(name="tpu-ok"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="bench-1b"),
                tpu=TPUProviderConfig(preset="bench-1b", tensor_parallelism=2),
            ),
        )
    )
    await rec.reconcile(("LLM", "default", "tpu-ok"))
    llm = store.get("LLM", "tpu-ok")
    assert llm.status.status_detail == "" or "Parallelism" not in llm.status.status_detail


async def test_llm_controller_tpu_quantize_mismatch_is_invalid(store):
    """quantizeWeights/quantizeKv are the same declarative-intent contract
    as the parallelism fields: a spec requesting quantized serving from a
    bf16 engine must fail validation, not silently serve unquantized."""
    from agentcontrolplane_tpu.api.resources import TPUProviderConfig

    class Bf16Engine:
        quantize = None
        quantize_kv = False

        class mesh:
            shape = {"tp": 1}

    class QuantEngine(Bf16Engine):
        quantize = "int8"
        quantize_kv = True

    class Factory:
        def __init__(self, engine):
            self.engine = engine

    rec = LLMReconciler(store, EventRecorder(store), Factory(Bf16Engine()), probe=False)
    for name, cfg in (
        ("q-weights", TPUProviderConfig(preset="bench-1b", quantize_weights=True)),
        ("q-legacy", TPUProviderConfig(preset="bench-1b", quantization="int8")),
        ("q-kv", TPUProviderConfig(preset="bench-1b", quantize_kv=True)),
    ):
        store.create(
            LLM(
                metadata=ObjectMeta(name=name),
                spec=LLMSpec(
                    provider="tpu",
                    parameters=BaseConfig(model="bench-1b"),
                    tpu=cfg,
                ),
            )
        )
        await rec.reconcile(("LLM", "default", name))
        llm = store.get("LLM", name)
        assert not llm.status.ready
        assert "quantize" in llm.status.status_detail.lower()

    rec_q = LLMReconciler(store, EventRecorder(store), Factory(QuantEngine()), probe=False)
    store.create(
        LLM(
            metadata=ObjectMeta(name="q-ok"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="bench-1b"),
                tpu=TPUProviderConfig(
                    preset="bench-1b", quantize_weights=True, quantize_kv=True
                ),
            ),
        )
    )
    await rec_q.reconcile(("LLM", "default", "q-ok"))
    llm = store.get("LLM", "q-ok")
    assert "quantize" not in llm.status.status_detail.lower()
