"""Task state-machine conformance tests, single reconcile step at a time.

Mirrors the reference's style (``task/task_controller_test.go``): each test
drives exactly one phase transition and asserts phases, requeue durations,
status fields, and emitted events.
"""

import pytest

from agentcontrolplane_tpu.api.resources import (
    LABEL_TASK,
    LABEL_TOOL_CALL_REQUEST,
    Message,
)
from agentcontrolplane_tpu.controllers.task import TaskReconciler, build_initial_context_window
from agentcontrolplane_tpu.humanlayer import LocalHumanLayerClientFactory
from agentcontrolplane_tpu.kernel import EventRecorder, Store, lease
from agentcontrolplane_tpu.llmclient import (
    LLMRequestError,
    MockLLMClient,
    MockLLMClientFactory,
    assistant,
    tool_call_message,
)

from ..fixtures import make_agent, make_llm, make_mcpserver, make_task, make_toolcall


class FakeMCPManager:
    def __init__(self, tools=None, results=None):
        self._tools = tools or {}
        self._results = results or {}
        self.calls = []

    def get_tools(self, name):
        return self._tools.get(name, [])

    async def call_tool(self, server, tool, args):
        self.calls.append((server, tool, args))
        result = self._results.get(f"{server}__{tool}", "ok")
        if isinstance(result, Exception):
            raise result
        return result


@pytest.fixture
def harness(store):
    recorder = EventRecorder(store)
    mock = MockLLMClient()
    factory = MockLLMClientFactory(mock)
    rec = TaskReconciler(
        store=store,
        recorder=recorder,
        llm_factory=factory,
        mcp_manager=FakeMCPManager(),
        hl_factory=LocalHumanLayerClientFactory(),
    )
    return store, rec, mock, recorder


def key(name):
    return ("Task", "default", name)


async def step(rec, name="test-task"):
    return await rec.reconcile(key(name))


async def test_empty_phase_initializes_and_persists_span(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    make_task(store)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "Initializing"
    assert task.status.span_context is not None
    assert len(task.status.span_context.trace_id) == 32
    assert result.requeue


async def test_agent_missing_goes_pending_with_requeue(harness):
    store, rec, mock, recorder = harness
    make_task(store, agent="missing-agent")
    await step(rec)  # '' -> Initializing
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "Pending"
    assert 'Waiting for Agent "missing-agent" to exist' in task.status.status_detail
    assert result.requeue_after == rec.requeue_delay
    reasons = [e.spec.reason for e in recorder.events_for(task)]
    assert "Waiting" in reasons


async def test_agent_not_ready_goes_pending(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store, ready=False)
    make_task(store)
    await step(rec)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "Pending"
    assert "to become ready" in task.status.status_detail
    assert result.requeue_after == rec.requeue_delay


async def test_ready_agent_builds_context_window(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store, system="system prompt here")
    make_task(store, user_message="hello")
    await step(rec)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "ReadyForLLM"
    assert [m.role for m in task.status.context_window] == ["system", "user"]
    assert task.status.context_window[0].content == "system prompt here"
    assert task.status.context_window[1].content == "hello"
    assert task.status.user_msg_preview == "hello"
    assert result.requeue


async def test_invalid_spec_fails_terminally(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    make_task(
        store,
        user_message="both",
        context_window=[Message(role="user", content="also this")],
    )
    await step(rec)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "Failed"
    assert task.status.status == "Error"
    assert "only one of" in task.status.error
    assert not result.requeue and result.requeue_after is None


async def test_final_answer_path(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    make_task(store, user_message="2+2?")
    mock.script.append(assistant("4"))
    await step(rec)
    await step(rec)
    result = await step(rec)  # ReadyForLLM -> FinalAnswer
    task = store.get("Task", "test-task")
    assert task.status.phase == "FinalAnswer"
    assert task.status.output == "4"
    assert task.status.context_window[-1].role == "assistant"
    assert task.status.context_window[-1].content == "4"
    assert task.status.message_count == 3
    assert not result.requeue and result.requeue_after is None
    # terminal: further reconciles are no-ops
    assert (await step(rec)).requeue_after is None


async def test_tool_calls_fan_out(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    from agentcontrolplane_tpu.api.resources import MCPTool

    rec.mcp_manager = FakeMCPManager(
        tools={"fetch": [MCPTool(name="fetch", description="fetch a url")]}
    )
    make_mcpserver(store, "fetch")
    make_agent(store, mcp_servers=["fetch"], resolved_tools={"fetch": ["fetch"]})
    make_task(store, user_message="fetch example.com")
    mock.script.append(
        tool_call_message(("fetch__fetch", {"url": "https://example.com"}))
    )
    await step(rec)
    await step(rec)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "ToolCallsPending"
    assert task.status.tool_call_request_id
    # the LLM saw the mangled MCP tool
    sent_tools = [t.function.name for t in mock.requests[0].tools]
    assert "fetch__fetch" in sent_tools

    tcs = store.list(
        "ToolCall",
        label_selector={
            LABEL_TASK: "test-task",
            LABEL_TOOL_CALL_REQUEST: task.status.tool_call_request_id,
        },
    )
    assert len(tcs) == 1
    tc = tcs[0]
    assert tc.metadata.name == f"test-task-{task.status.tool_call_request_id}-tc-01"
    assert tc.spec.tool_type == "MCP"
    assert tc.spec.tool_ref.name == "fetch__fetch"
    assert tc.metadata.owner_references[0].name == "test-task"
    assert result.requeue_after == rec.requeue_delay


async def test_tool_calls_join_appends_results_in_order(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    task = make_task(store)
    # fabricate ToolCallsPending with two completed tool calls
    task.status.phase = "ToolCallsPending"
    task.status.tool_call_request_id = "req1234"
    task.status.context_window = [
        Message(role="system", content="s"),
        Message(role="user", content="u"),
    ]
    store.update_status(task)
    labels = {LABEL_TASK: "test-task", LABEL_TOOL_CALL_REQUEST: "req1234"}
    for name, result_text, phase in [
        ("tc-01", "result one", "Succeeded"),
        ("tc-02", "Rejected: no", "ToolCallRejected"),
    ]:
        tc = make_toolcall(store, name=f"test-task-req1234-{name}", labels=labels)
        tc.status.phase = phase
        tc.status.status = "Succeeded"
        tc.status.result = result_text
        store.update_status(tc)

    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "ReadyForLLM"
    tool_msgs = [m for m in task.status.context_window if m.role == "tool"]
    assert [m.content for m in tool_msgs] == ["result one", "Rejected: no"]
    assert result.requeue


async def test_tool_calls_pending_waits_for_completion(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    task = make_task(store)
    task.status.phase = "ToolCallsPending"
    task.status.tool_call_request_id = "req1234"
    store.update_status(task)
    labels = {LABEL_TASK: "test-task", LABEL_TOOL_CALL_REQUEST: "req1234"}
    make_toolcall(store, name="test-task-req1234-tc-01", labels=labels)  # phase ""
    result = await step(rec)
    assert store.get("Task", "test-task").status.phase == "ToolCallsPending"
    assert result.requeue_after == rec.requeue_delay


async def test_llm_4xx_fails_terminally(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    make_task(store)
    mock.script.append(LLMRequestError(401, "bad api key"))
    await step(rec)
    await step(rec)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "Failed"
    assert "401" in task.status.error
    assert result.requeue_after is None and not result.requeue
    reasons = [e.spec.reason for e in recorder.events_for(task)]
    assert "LLMRequestFailed" in reasons


async def test_llm_5xx_retries_keeping_phase(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    make_task(store)
    mock.script.append(LLMRequestError(503, "overloaded"))
    await step(rec)
    await step(rec)
    result = await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "ReadyForLLM"  # phase kept
    assert task.status.status == "Error"
    # 503/429 retries are jittered in [delay, 2*delay) so shed tasks don't
    # re-converge on the engine in one synchronized wave
    assert rec.requeue_delay <= result.requeue_after < 2 * rec.requeue_delay
    # next attempt succeeds
    mock.script.append(assistant("recovered"))
    await step(rec)
    assert store.get("Task", "test-task").status.phase == "FinalAnswer"


async def test_lease_held_by_other_replica_blocks_llm_send(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store)
    make_task(store)
    await step(rec)
    await step(rec)
    lease.try_acquire(store, "task-llm-test-task", "other-pod", ttl=30)
    result = await step(rec)
    assert store.get("Task", "test-task").status.phase == "ReadyForLLM"
    assert result.requeue_after == rec.requeue_delay
    assert mock.requests == []  # no LLM call happened


def test_build_initial_context_window_prepends_system_iff_absent():
    # provided window without system -> system prepended
    win = build_initial_context_window(
        [Message(role="user", content="u")], "SYS", ""
    )
    assert [m.role for m in win] == ["system", "user"]
    assert win[0].content == "SYS"
    # provided window with system -> untouched
    win = build_initial_context_window(
        [Message(role="system", content="custom"), Message(role="user", content="u")],
        "SYS",
        "",
    )
    assert win[0].content == "custom"
    # no window -> [system, user]
    win = build_initial_context_window([], "SYS", "hello")
    assert [(m.role, m.content) for m in win] == [("system", "SYS"), ("user", "hello")]


async def test_context_window_task_spec(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    make_agent(store, system="AGENT SYS")
    make_task(
        store,
        user_message=None,
        context_window=[
            Message(role="user", content="continuing conversation"),
        ],
    )
    await step(rec)
    await step(rec)
    task = store.get("Task", "test-task")
    assert task.status.phase == "ReadyForLLM"
    assert task.status.context_window[0].role == "system"
    assert task.status.context_window[0].content == "AGENT SYS"
    assert task.status.user_msg_preview == "continuing conversation"


def test_compact_window_protocol_safe():
    from agentcontrolplane_tpu.api.resources import MessageToolCall, ToolCallFunction
    from agentcontrolplane_tpu.controllers.task import compact_window

    window = [Message(role="system", content="sys")]
    for i in range(6):
        window.append(
            Message(
                role="assistant", content="",
                tool_calls=[MessageToolCall(id=f"c{i}", function=ToolCallFunction(name="t__x"))],
            )
        )
        window.append(Message(role="tool", content=f"r{i}", tool_call_id=f"c{i}"))
    window.append(Message(role="user", content="latest question"))

    out = compact_window(window, max_messages=6)
    assert len(out) <= 6
    assert out[0].content == "sys"
    assert "elided" in out[1].content
    # the kept suffix never starts with an orphaned tool result
    assert out[2].role != "tool"
    # untouched when under the cap or policy disabled
    assert compact_window(window, 0) == window
    assert compact_window(window[:3], 10) == window[:3]


async def test_context_policy_applied_to_llm_request(harness):
    store, rec, mock, recorder = harness
    make_llm(store)
    agent = make_agent(store)
    from agentcontrolplane_tpu.api.resources import ContextPolicy

    agent = store.get("Agent", "test-agent")
    agent.spec.context_policy = ContextPolicy(max_messages=4)
    store.update(agent)
    # fabricate a long checkpointed conversation mid-loop
    task = make_task(store)
    task.status.phase = "ReadyForLLM"
    task.status.context_window = (
        [Message(role="system", content="s")]
        + [Message(role="user" if i % 2 == 0 else "assistant", content=f"m{i}") for i in range(10)]
    )
    store.update_status(task)
    mock.script.append(assistant("done"))
    await step(rec)
    sent = mock.requests[0].messages
    assert len(sent) <= 4
    assert any("elided" in m.content for m in sent)
    # the persisted history kept EVERYTHING (checkpoint intact) + new answer
    stored = store.get("Task", "test-task").status.context_window
    assert len(stored) == 12


async def test_engineless_replica_defers_tpu_tasks(harness):
    """Multi-replica: a follower with no serving engine must leave a
    provider:tpu task for the engine-owning replica — quiet requeue with a
    status detail, no failed send, no error event, lease released."""
    store, rec, mock, recorder = harness
    make_llm(store, name="tpu-llm", provider="tpu")
    make_agent(store, name="agent", llm="tpu-llm")
    make_task(store, name="t", agent="agent", user_message="hi")
    await step(rec, "t")  # '' -> Initializing
    await step(rec, "t")  # -> ReadyForLLM

    assert getattr(rec.llm_factory, "engine", "missing") is None  # follower shape
    res = await step(rec, "t")
    task = store.get("Task", "t")
    assert task.status.phase == "ReadyForLLM"  # untouched, not Failed
    assert "engine-serving replica" in task.status.status_detail
    assert res.requeue_after == rec.requeue_delay
    assert mock.requests == []  # nothing was sent anywhere
    # the lease is released so the owner can take it immediately
    assert lease.try_acquire(store, "task-llm-t", "engine-owner")
