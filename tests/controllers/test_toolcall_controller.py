"""ToolCall state-machine conformance tests (reference: toolcall/*_test.go)."""

import pytest

from agentcontrolplane_tpu.api.resources import LABEL_PARENT_TOOLCALL
from agentcontrolplane_tpu.controllers.toolcall import ToolCallReconciler
from agentcontrolplane_tpu.humanlayer import LocalHumanBackend, LocalHumanLayerClientFactory
from agentcontrolplane_tpu.kernel import EventRecorder, Store

from ..fixtures import (
    make_agent,
    make_contactchannel,
    make_llm,
    make_mcpserver,
    make_secret,
    make_task,
    make_toolcall,
)
from .test_task_controller import FakeMCPManager


@pytest.fixture
def harness(store):
    recorder = EventRecorder(store)
    backend = LocalHumanBackend()
    mcp = FakeMCPManager(results={"fetch__fetch": "<html>example</html>"})
    rec = ToolCallReconciler(
        store=store,
        recorder=recorder,
        mcp_manager=mcp,
        hl_factory=LocalHumanLayerClientFactory(backend),
    )
    return store, rec, backend, mcp, recorder


def key(name="test-task-abc1234-tc-01"):
    return ("ToolCall", "default", name)


async def drive_to_ready(rec, name="test-task-abc1234-tc-01"):
    await rec.reconcile(key(name))  # '' -> Pending/Pending (+ span)
    await rec.reconcile(key(name))  # -> Pending/Ready


async def test_initialize_then_setup(harness):
    store, rec, backend, mcp, recorder = harness
    make_task(store)
    make_toolcall(store)
    result = await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert (tc.status.phase, tc.status.status) == ("Pending", "Pending")
    assert tc.status.span_context is not None
    assert result.requeue
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert (tc.status.phase, tc.status.status) == ("Pending", "Ready")


async def test_mcp_execution_without_approval(harness):
    store, rec, backend, mcp, recorder = harness
    make_task(store)
    make_mcpserver(store, "fetch")  # no approval channel
    make_toolcall(store)
    await drive_to_ready(rec)
    result = await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Succeeded"
    assert tc.status.result == "<html>example</html>"
    assert tc.status.completion_time is not None
    assert mcp.calls == [("fetch", "fetch", {"url": "https://example.com"})]
    assert result.requeue_after is None


async def test_mcp_failure_marks_failed_with_error_result(harness):
    store, rec, backend, mcp, recorder = harness
    mcp._results["fetch__fetch"] = RuntimeError("connection refused")
    make_task(store)
    make_mcpserver(store, "fetch")
    make_toolcall(store)
    await drive_to_ready(rec)
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Failed"
    assert "connection refused" in tc.status.error
    assert tc.status.result.startswith("error:")


async def test_approval_gate_approve_then_execute(harness):
    store, rec, backend, mcp, recorder = harness
    make_secret(store)
    make_task(store)
    make_contactchannel(store, "approvals")
    make_mcpserver(store, "fetch", approval_channel="approvals")
    make_toolcall(store)
    await drive_to_ready(rec)

    result = await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "AwaitingHumanApproval"
    assert tc.status.external_call_id
    assert result.requeue_after == rec.poll_interval
    pending = backend.pending_approvals()
    assert len(pending) == 1 and pending[0].fn == "fetch__fetch"

    # still pending -> keeps polling
    result = await rec.reconcile(key())
    assert result.requeue_after == rec.poll_interval

    backend.approve(tc.status.external_call_id, "go ahead")
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "ReadyToExecuteApprovedTool"
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Succeeded"
    assert tc.status.result == "<html>example</html>"


async def test_approval_rejection_is_a_successful_tool_result(harness):
    store, rec, backend, mcp, recorder = harness
    make_secret(store)
    make_task(store)
    make_contactchannel(store, "approvals")
    make_mcpserver(store, "fetch", approval_channel="approvals")
    make_toolcall(store)
    await drive_to_ready(rec)
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    backend.reject(tc.status.external_call_id, "too dangerous")
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "ToolCallRejected"
    assert tc.status.status == "Succeeded"  # the LLM sees the rejection
    assert tc.status.result == "Rejected: too dangerous"
    assert mcp.calls == []  # tool never executed


async def test_delegate_spawns_child_task_and_joins(harness):
    store, rec, backend, mcp, recorder = harness
    make_llm(store)
    make_agent(store, name="researcher", description="does research")
    make_task(store)
    make_toolcall(
        store,
        tool="delegate_to_agent__researcher",
        tool_type="DelegateToAgent",
        arguments='{"message": "find the answer"}',
    )
    await drive_to_ready(rec)
    result = await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "AwaitingSubAgent"
    children = store.list("Task", label_selector={LABEL_PARENT_TOOLCALL: tc.name})
    assert len(children) == 1
    child = children[0]
    assert child.spec.agent_ref.name == "researcher"
    assert child.spec.user_message == "find the answer"
    assert child.metadata.owner_references[0].name == tc.name

    # idempotent under requeue: no duplicate child
    await rec.reconcile(key())
    assert len(store.list("Task", label_selector={LABEL_PARENT_TOOLCALL: tc.name})) == 1

    # child completes -> toolcall succeeds with child's output
    child.status.phase = "FinalAnswer"
    child.status.output = "the answer is 42"
    store.update_status(child)
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Succeeded"
    assert tc.status.result == "the answer is 42"


async def test_delegate_child_failure_propagates(harness):
    store, rec, backend, mcp, recorder = harness
    make_llm(store)
    make_agent(store, name="researcher")
    make_task(store)
    make_toolcall(
        store,
        tool="delegate_to_agent__researcher",
        tool_type="DelegateToAgent",
        arguments='{"message": "do it"}',
    )
    await drive_to_ready(rec)
    await rec.reconcile(key())
    child = store.list("Task", label_selector={LABEL_PARENT_TOOLCALL: "test-task-abc1234-tc-01"})[0]
    child.status.phase = "Failed"
    child.status.error = "llm exploded"
    store.update_status(child)
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Failed"
    assert "llm exploded" in tc.status.error


async def test_human_contact_roundtrip(harness):
    store, rec, backend, mcp, recorder = harness
    make_secret(store)
    make_task(store)
    make_contactchannel(store, "oncall")
    make_toolcall(
        store,
        tool="oncall__human_contact_email",
        tool_type="HumanContact",
        arguments='{"message": "should I deploy?"}',
    )
    await drive_to_ready(rec)
    result = await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "AwaitingHumanInput"
    assert result.requeue_after == rec.poll_interval
    assert backend.pending_contacts()[0].message == "should I deploy?"

    backend.respond(tc.status.external_call_id, "yes, ship it")
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Succeeded"
    assert tc.status.result == "yes, ship it"


async def test_unknown_tool_type_fails(harness):
    store, rec, backend, mcp, recorder = harness
    make_task(store)
    make_toolcall(store, tool="unmangled-name")  # MCP but no server__tool form
    await drive_to_ready(rec)
    await rec.reconcile(key())
    tc = store.get("ToolCall", "test-task-abc1234-tc-01")
    assert tc.status.phase == "Failed"
    assert "not of the form" in tc.status.error
