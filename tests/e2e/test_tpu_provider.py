"""provider: tpu end-to-end — the north-star slice at test scale.

A real Operator where the LLM seam resolves to the in-process JAX engine
(tiny model, tp=2 over the virtual CPU mesh): concurrent Task CRs are
continuously batched into one decode stream and every task reaches
FinalAnswer with engine-generated text. (Output quality is meaningless with
random weights; the invariants are flow + batching + checkpointing.)
"""

import asyncio
import dataclasses
import os

import pytest

import jax

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import LLM, BaseConfig, LLMSpec, TPUProviderConfig
from agentcontrolplane_tpu.engine.engine import Engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.kernel import wait_for
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.operator import Operator, OperatorOptions
from agentcontrolplane_tpu.parallel.mesh import make_mesh

from ..fixtures import make_agent, make_task, setup_with_status


@pytest.fixture(scope="module")
def engine():
    cfg = dataclasses.replace(
        PRESETS["tiny"], vocab_size=512, max_seq_len=512, n_kv_heads=2
    )
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=cfg,
        tokenizer=ByteTokenizer(),
        mesh=mesh,
        max_slots=8,
        max_ctx=256,
        prefill_buckets=(128, 256),
    )
    eng.start()
    yield eng
    eng.stop()


async def test_concurrent_tasks_served_by_tpu_engine(engine):
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=engine,
        ),
    )
    op.task_reconciler.requeue_delay = 0.02
    store = op.store
    setup_with_status(
        store,
        LLM(
            metadata=ObjectMeta(name="tpu-llm"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="tiny", max_tokens=12, temperature=0.0),
                tpu=TPUProviderConfig(preset="tiny"),
            ),
        ),
        lambda o: (
            setattr(o.status, "ready", True),
            setattr(o.status, "status", "Ready"),
        ),
    )
    make_agent(store, llm="tpu-llm", system="continue the text")
    n = 8
    for i in range(n):
        make_task(store, name=f"tpu-task-{i}", user_message=f"prompt {i}")
    await op.start()
    try:
        done = []
        for i in range(n):
            t = await wait_for(
                store, "Task", f"tpu-task-{i}", "default",
                lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=120,
            )
            done.append(t)
        assert all(t.status.phase == "FinalAnswer" for t in done)
        # every conversation got an engine-produced assistant turn,
        # checkpointed in status
        for t in done:
            assert [m.role for m in t.status.context_window] == ["system", "user", "assistant"]
        # the engine actually batched: it generated tokens for all tasks
        assert engine.tokens_generated >= n
    finally:
        await op.stop()


async def test_llm_controller_validates_tpu_provider(engine):
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=engine,
        ),
    )
    store = op.store
    store.create(
        LLM(
            metadata=ObjectMeta(name="bad-tpu"),
            spec=LLMSpec(provider="tpu", parameters=BaseConfig()),  # no tpu block
        )
    )
    await op.llm_reconciler.reconcile(("LLM", "default", "bad-tpu"))
    llm = store.get("LLM", "bad-tpu")
    assert llm.status.status == "Error"
    assert "requires a tpu config" in llm.status.status_detail


@pytest.mark.skipif(
    not os.environ.get("ACP_STRESS"), reason="set ACP_STRESS=1 for the full-width run"
)
async def test_64_concurrent_tasks_stress(engine):
    """BASELINE config #5 at full width: 64 concurrent Task CRs continuously
    batched into one decode stream (tiny model; CPU). Opt-in: slow."""
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=engine,
        ),
    )
    op.task_reconciler.requeue_delay = 0.05
    store = op.store
    setup_with_status(
        store,
        LLM(
            metadata=ObjectMeta(name="tpu-llm"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="tiny", max_tokens=8, temperature=0.0),
                tpu=TPUProviderConfig(preset="tiny"),
            ),
        ),
        lambda o: (
            setattr(o.status, "ready", True),
            setattr(o.status, "status", "Ready"),
        ),
    )
    make_agent(store, llm="tpu-llm", system="continue")
    n = 64
    for i in range(n):
        make_task(store, name=f"stress-{i}", user_message=f"p{i}")
    await op.start()
    try:
        for i in range(n):
            t = await wait_for(
                store, "Task", f"stress-{i}", "default",
                lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=600,
            )
            assert t.status.phase == "FinalAnswer", t.status.error
    finally:
        await op.stop()


async def test_tool_choice_required_forces_tool_call(engine):
    """tool_choice "required" (LLM.spec.providerConfig): the engine
    teacher-forces the tool-call envelope and grammar-constrains the rest,
    so even a RANDOM model reliably drives the Task into ToolCallsPending
    with a real ToolCall CR — the full create->first-ToolCall path the TTFT
    baseline metric measures."""
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=engine,
        ),
    )
    op.task_reconciler.requeue_delay = 0.02
    op.toolcall_reconciler.poll_interval = 0.02
    store = op.store
    setup_with_status(
        store,
        LLM(
            metadata=ObjectMeta(name="tpu-forced"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="tiny", max_tokens=40, temperature=1.0),
                tpu=TPUProviderConfig(preset="tiny"),
                provider_config={"tool_choice": "required"},
            ),
        ),
        lambda o: (
            setattr(o.status, "ready", True),
            setattr(o.status, "status", "Ready"),
        ),
    )
    # the delegate tool needs no MCP subprocess
    make_agent(store, name="leaf", llm="tpu-forced", system="leaf")
    make_agent(
        store, name="rooter", llm="tpu-forced", system="delegate",
        sub_agents=("leaf",),
    )
    make_task(store, name="forced-task", agent="rooter", user_message="do the thing")
    await op.start()
    try:
        # poll for the ToolCall CR itself: ToolCallsPending is transient
        # (the delegate may resolve and loop the task back to ReadyForLLM)
        import time as _time

        deadline = _time.monotonic() + 120
        ours = []
        while _time.monotonic() < deadline and not ours:
            ours = store.list(
                "ToolCall", "default",
                label_selector={"acp.tpu/task": "forced-task"},
            )
            await asyncio.sleep(0.05)
        assert len(ours) >= 1
        assert ours[0].spec.tool_ref.name == "delegate_to_agent__leaf"
        import json as _json

        _json.loads(ours[0].spec.arguments)  # grammar guaranteed this
    finally:
        await op.stop()


async def test_human_contact_flow_driven_by_tpu_engine(engine):
    """BASELINE config 4 with provider: tpu — the engine's forced tool call
    targets the human-contact tool, the ToolCall goes AwaitingHumanInput
    against the in-tree human backend, a human responds, and the answer
    joins the Task's context window."""
    from ..fixtures import make_contactchannel, make_secret

    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=engine,
        ),
    )
    op.task_reconciler.requeue_delay = 0.02
    op.toolcall_reconciler.poll_interval = 0.02
    store = op.store
    make_secret(store)  # the channel's api key; revalidation checks it
    make_contactchannel(store, name="oncall")
    setup_with_status(
        store,
        LLM(
            metadata=ObjectMeta(name="tpu-hc"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="tiny", max_tokens=40, temperature=1.0),
                tpu=TPUProviderConfig(preset="tiny"),
                # force the channel tool explicitly
                provider_config={"tool_choice": "oncall__human_contact_email"},
            ),
        ),
        lambda o: (
            setattr(o.status, "ready", True),
            setattr(o.status, "status", "Ready"),
        ),
    )
    make_agent(store, name="asker", llm="tpu-hc", system="ask the human",
               channels=("oncall",))
    make_task(store, name="hc-task", agent="asker", user_message="need sign-off")
    await op.start()
    try:
        # engine-driven forced call -> ToolCall CR -> AwaitingHumanInput
        deadline_tc = None
        for _ in range(1200):
            tcs = store.list(
                "ToolCall", "default", label_selector={"acp.tpu/task": "hc-task"}
            )
            if tcs and tcs[0].status.phase == "AwaitingHumanInput":
                deadline_tc = tcs[0]
                break
            await asyncio.sleep(0.1)
        assert deadline_tc is not None, "ToolCall never reached AwaitingHumanInput"
        assert deadline_tc.spec.tool_type == "HumanContact"

        # the human answers through the in-tree backend
        pending = op.human_backend.pending_contacts()
        assert pending
        op.human_backend.respond(pending[0].call_id, "approved, proceed")

        def tool_result_joined(t) -> bool:
            return any(
                m.role == "tool" and "approved, proceed" in (m.content or "")
                for m in t.status.context_window
            )

        await wait_for(
            store, "Task", "hc-task", "default", tool_result_joined, timeout=120,
        )
    finally:
        await op.stop()
