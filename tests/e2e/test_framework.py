"""E2E: real Operator with ALL controllers running concurrently.

Mirrors the reference's ``test/e2e/framework.go`` + getting_started suite:
no external services — the LLM is a scripted mock behind the factory seam,
humans are the in-tree LocalHumanBackend. Covers the baseline configs (#1-#4
from BASELINE.md): hello-world, tool loop, sub-agent delegation, and async
human approval.
"""

import asyncio

import pytest

from agentcontrolplane_tpu.api.resources import LABEL_AGENT, MCPTool
from agentcontrolplane_tpu.kernel import wait_for
from agentcontrolplane_tpu.llmclient import (
    MockLLMClient,
    MockLLMClientFactory,
    assistant,
    tool_call_message,
)
from agentcontrolplane_tpu.operator import Operator, OperatorOptions

from ..fixtures import (
    make_agent,
    make_contactchannel,
    make_llm,
    make_mcpserver,
    make_secret,
    make_task,
)


class E2EHarness:
    def __init__(self):
        self.mock = MockLLMClient()
        self.operator = Operator(
            options=OperatorOptions(
                enable_rest=False,
                llm_probe=False,
                verify_channel_credentials=False,
            ),
            llm_factory=MockLLMClientFactory(self.mock),
        )
        # speed up polling for tests
        self.operator.task_reconciler.requeue_delay = 0.02
        self.operator.task_reconciler.notify_backoff = (0.01, 0.01, 0.01)
        self.operator.toolcall_reconciler.poll_interval = 0.02
        self.store = self.operator.store
        self.backend = self.operator.human_backend

    async def __aenter__(self):
        await self.operator.start()
        return self

    async def __aexit__(self, *exc):
        await self.operator.stop()


class E2EMCP:
    """In-memory MCP 'server' satisfying the full MCPManager seam (including
    the connection-pool view the MCPServer controller keeps alive)."""

    class _Client:
        alive = True

    def __init__(self, tools, results):
        self._tools = tools
        self._results = results
        self.calls = []

    def get_tools(self, name):
        return self._tools.get(name, [])

    async def call_tool(self, server, tool, args):
        self.calls.append((server, tool, args))
        return self._results[f"{server}__{tool}"]

    def get_connection(self, name):
        from agentcontrolplane_tpu.mcp.manager import MCPConnection

        if name not in self._tools:
            return None
        return MCPConnection(name=name, client=self._Client(), tools=self._tools[name])

    async def connect_server(self, server):
        conn = self.get_connection(server.metadata.name)
        if conn is None:
            raise RuntimeError(f"no scripted tools for {server.metadata.name}")
        return conn

    async def disconnect_server(self, name):
        pass

    def install(self, operator):
        operator.task_reconciler.mcp_manager = self
        operator.toolcall_reconciler.mcp_manager = self
        operator.mcpserver_reconciler.mcp_manager = self


async def test_config1_hello_world_single_turn():
    async with E2EHarness() as h:
        make_llm(h.store)
        make_agent(h.store, ready=False)  # agent controller will validate it
        h.mock.script.append(assistant("Paris"))
        make_task(h.store, user_message="capital of France?")
        task = await wait_for(
            h.store, "Task", "test-task", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=10,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "Paris"
        # conversation checkpointed in status: system, user, assistant
        assert [m.role for m in task.status.context_window] == ["system", "user", "assistant"]


async def test_config2_mcp_tool_loop():
    async with E2EHarness() as h:
        mcp = E2EMCP(
            tools={"fetch": [MCPTool(name="fetch", description="fetch url")]},
            results={"fetch__fetch": "<html>hello</html>"},
        )
        mcp.install(h.operator)
        make_llm(h.store)
        make_mcpserver(h.store, "fetch")
        make_agent(h.store, mcp_servers=["fetch"], resolved_tools={"fetch": ["fetch"]})
        h.mock.script.append(tool_call_message(("fetch__fetch", {"url": "https://x.com"})))
        h.mock.script.append(assistant("the page says hello"))
        make_task(h.store, user_message="fetch x.com and summarize")
        task = await wait_for(
            h.store, "Task", "test-task", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=10,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "the page says hello"
        assert mcp.calls == [("fetch", "fetch", {"url": "https://x.com"})]
        roles = [m.role for m in task.status.context_window]
        assert roles == ["system", "user", "assistant", "tool", "assistant"]
        # the second LLM request saw the tool result
        tool_msg = h.mock.requests[1].messages[3]
        assert tool_msg.role == "tool" and tool_msg.content == "<html>hello</html>"


async def test_config3_sub_agent_delegation():
    async with E2EHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="researcher", description="does research", ready=False)
        make_agent(h.store, name="main", sub_agents=["researcher"], ready=False)

        def router(messages, tools):
            tool_names = [t.function.name for t in tools]
            if "delegate_to_agent__researcher" in tool_names and len(messages) == 2:
                return tool_call_message(
                    ("delegate_to_agent__researcher", {"message": "look this up"})
                ).model_copy()
            if messages[0].content.startswith("you are"):  # sub-agent task
                if any(m.role == "tool" for m in messages):
                    return assistant("synthesized: deep answer")
                if len(messages) == 2 and messages[1].content == "look this up":
                    return assistant("deep answer")
            return assistant("synthesized: deep answer")

        h.mock.default = None
        h.mock.script = [router, router, router]
        make_task(h.store, name="parent-task", agent="main", user_message="research this")
        task = await wait_for(
            h.store, "Task", "parent-task", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=10,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "synthesized: deep answer"
        # a child task ran the full stack and completed
        children = [
            t for t in h.store.list("Task")
            if t.name.startswith("delegate-") and t.status.phase == "FinalAnswer"
        ]
        assert len(children) == 1
        assert children[0].status.output == "deep answer"


async def test_config4_human_approval_async():
    async with E2EHarness() as h:
        mcp = E2EMCP(
            tools={"deploy": [MCPTool(name="ship", description="deploy to prod")]},
            results={"deploy__ship": "deployed v42"},
        )
        mcp.install(h.operator)
        make_secret(h.store)
        make_llm(h.store)
        make_contactchannel(h.store, "approvals")
        make_mcpserver(h.store, "deploy", tools=("ship",), approval_channel="approvals")
        make_agent(
            h.store, mcp_servers=["deploy"], resolved_tools={"deploy": ["ship"]}
        )
        h.mock.script.append(tool_call_message(("deploy__ship", {"version": "v42"})))
        h.mock.script.append(assistant("shipped!"))
        make_task(h.store, user_message="deploy v42")

        # wait until the approval shows up in the in-tree backend
        deadline = 50
        while not h.backend.pending_approvals() and deadline:
            await asyncio.sleep(0.05)
            deadline -= 1
        pending = h.backend.pending_approvals()
        assert pending and pending[0].fn == "deploy__ship"
        assert mcp.calls == []  # nothing executed before approval

        h.backend.approve(pending[0].call_id, "lgtm")
        task = await wait_for(
            h.store, "Task", "test-task", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=10,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "shipped!"
        assert mcp.calls == [("deploy", "ship", {"version": "v42"})]


async def test_operator_restart_resumes_in_flight_task(tmp_path):
    """Kill the operator mid-conversation; a fresh operator on the same
    sqlite store finishes the task (the defining checkpoint/resume move)."""
    from agentcontrolplane_tpu.llmclient import LLMRequestError

    db = str(tmp_path / "op.db")
    # op1's provider is "down" (retryable 503s), so the task parks in
    # ReadyForLLM — exactly the state a crashed pod would leave behind.
    mock = MockLLMClient(default=None)
    mock.script = [LLMRequestError(503, "provider down") for _ in range(1000)]
    op1 = Operator(
        options=OperatorOptions(db_path=db, enable_rest=False, llm_probe=False),
        llm_factory=MockLLMClientFactory(mock),
    )
    op1.task_reconciler.requeue_delay = 0.02
    make_llm(op1.store)
    make_agent(op1.store)
    make_task(op1.store, user_message="hello")
    await op1.start()
    await wait_for(
        op1.store, "Task", "test-task", "default",
        lambda t: t.status.phase == "ReadyForLLM", timeout=10,
    )
    await op1.manager.stop()
    op1.store.close()

    mock2 = MockLLMClient(script=[assistant("resumed and finished")])
    op2 = Operator(
        options=OperatorOptions(db_path=db, enable_rest=False, llm_probe=False),
        llm_factory=MockLLMClientFactory(mock2),
    )
    op2.task_reconciler.requeue_delay = 0.02
    await op2.start()
    try:
        task = await wait_for(
            op2.store, "Task", "test-task", "default",
            lambda t: t.status.phase == "FinalAnswer", timeout=10,
        )
        assert task.status.output == "resumed and finished"
        # context window survived the restart intact
        assert [m.role for m in task.status.context_window] == ["system", "user", "assistant"]
    finally:
        await op2.stop()
