"""BASELINE config 2 through a real checkpoint DIRECTORY: an on-disk HF
checkpoint (config.json + safetensors + byte-level-BPE tokenizer.json) is
loaded by engine/weights.py, tokenized by HFTokenizer, served by the engine,
and drives the multi-turn MCP stdio fetch loop — Task -> forced tool call ->
ToolCall CR -> real MCP subprocess -> tool result joined back into the
context window.

This is the first place weights.py + HFTokenizer + constrain + toolparse +
the MCP manager all meet in ONE flow (VERDICT r1 #4's shape, scaled to a
tiny random checkpoint since real Llama weights can't ship in this image;
the opt-in ACP_REAL_CHECKPOINT env points the same flow at a real one).
"""

import json
import os
import sys

import pytest

import jax

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LLM,
    BaseConfig,
    LLMSpec,
    MCPServer,
    MCPServerSpec,
    TPUProviderConfig,
)
from agentcontrolplane_tpu.engine.engine import Engine
from agentcontrolplane_tpu.engine.tokenizer import EOS, EOT, HFTokenizer
from agentcontrolplane_tpu.engine.weights import load_safetensors_dir
from agentcontrolplane_tpu.kernel import wait_for
from agentcontrolplane_tpu.operator import Operator, OperatorOptions
from agentcontrolplane_tpu.parallel.mesh import make_mesh

from ..fixtures import make_agent, make_task, setup_with_status

ECHO_SERVER = os.path.join(os.path.dirname(__file__), "..", "mcp", "echo_server.py")


@pytest.fixture(scope="module")
def checkpoint_dir(tmp_path_factory):
    """Generate a genuine HF checkpoint directory: trained byte-level BPE
    tokenizer.json + LlamaForCausalLM safetensors + config.json."""
    torch = pytest.importorskip("torch")
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    path = tmp_path_factory.mktemp("ckpt")

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    corpus = [
        '{"name": "echo__echo", "arguments": {"message": "hello"}}',
        "fetch the page and echo the result please",
        "tool call assistant system user json",
    ] * 50
    trainer = trainers.BpeTrainer(
        vocab_size=384,
        special_tokens=[EOT, EOS],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(corpus, trainer)
    tok.save(str(path / "tokenizer.json"))

    vocab = tok.get_vocab_size()
    hf_config = HFConfig(
        vocab_size=vocab,
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        intermediate_size=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        max_position_embeddings=512,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf_config).save_pretrained(str(path), safe_serialization=True)
    return str(path)


async def test_checkpoint_dir_drives_mcp_fetch_loop(checkpoint_dir):
    params, config = load_safetensors_dir(checkpoint_dir)
    tokenizer = HFTokenizer(os.path.join(checkpoint_dir, "tokenizer.json"))
    assert tokenizer.vocab_size == config.vocab_size
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    engine = Engine(
        config=config,
        params=params,
        tokenizer=tokenizer,
        mesh=mesh,
        max_slots=4,
        max_ctx=512,
        prefill_buckets=(256, 512),
        decode_block_size=4,
    )
    engine.start()
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=engine,
        ),
    )
    op.task_reconciler.requeue_delay = 0.02
    op.toolcall_reconciler.poll_interval = 0.02
    store = op.store
    try:
        store.create(
            MCPServer(
                metadata=ObjectMeta(name="echo"),
                spec=MCPServerSpec(
                    transport="stdio", command=sys.executable, args=[ECHO_SERVER]
                ),
            )
        )
        setup_with_status(
            store,
            LLM(
                metadata=ObjectMeta(name="ckpt-llm"),
                spec=LLMSpec(
                    provider="tpu",
                    parameters=BaseConfig(model="ckpt", max_tokens=48, temperature=0.8),
                    tpu=TPUProviderConfig(preset="tiny"),
                    # force the MCP echo tool: the loop is deterministic even
                    # with random weights
                    provider_config={"tool_choice": "echo__echo"},
                ),
            ),
            lambda o: (
                setattr(o.status, "ready", True),
                setattr(o.status, "status", "Ready"),
            ),
        )
        await op.start()
        # the real MCPServer controller connects + discovers tools
        await wait_for(
            store, "MCPServer", "echo", "default",
            lambda s: s.status.connected, timeout=30,
        )
        make_agent(
            store, name="fetcher", llm="ckpt-llm", system="use the echo tool",
            mcp_servers=("echo",), resolved_tools={"echo": ["echo", "env", "fail"]},
        )
        make_task(store, name="fetch-task", agent="fetcher", user_message="go")

        def tool_result_joined(t) -> bool:
            return any(
                m.role == "tool" and m.content.startswith("echo:")
                for m in t.status.context_window
            )

        t = await wait_for(
            store, "Task", "fetch-task", "default", tool_result_joined, timeout=180,
        )
        # the assistant turn before the tool result is a parseable forced call
        calls = [
            tc
            for m in t.status.context_window
            if m.role == "assistant" and m.tool_calls
            for tc in m.tool_calls
        ]
        assert calls and calls[0].function.name == "echo__echo"
        json.loads(calls[0].function.arguments)
    finally:
        await op.stop()
        engine.stop()
