"""E2E: TWO operator OS processes share one served store; the lease holder is
SIGKILLed mid-flight and the survivor adopts its task.

This is the cross-process realization of the reference's headline durability
property — "a surviving pod adopts a dead pod's in-flight task"
(acp/internal/controller/task/state_machine.go:1069-1145,
acp/docs/distributed-locking.md:84-150). Single-process lease tests can fake
two identities; only real processes prove the kill/adopt path end to end.

Topology: this test process owns the Store and serves it over a unix socket
(StoreServer); replicas A and B are `multireplica_worker.py` subprocesses
running full operators over RemoteStore. A's mock LLM hangs 120 s, so A
acquires the `task-llm-<name>` lease and parks mid-send; B's answers
instantly but cannot acquire while A's lease is live. SIGKILL A -> its lease
expires (ttl 15 s) -> B adopts and finishes the task.
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys


from agentcontrolplane_tpu.kernel import Store, StoreServer, wait_for
from agentcontrolplane_tpu.testing import make_agent, make_llm, make_task

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "multireplica_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def _spawn_worker(extra_argv: list[str], identity: str) -> subprocess.Popen:
    """Spawn a multireplica_worker process and wait (bounded) for READY;
    the process is killed, not leaked, if startup fails or times out."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"  # replicas never touch the accelerator
    proc = subprocess.Popen(
        [sys.executable, _WORKER, *extra_argv],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
        env=env,
    )

    def wait_ready() -> str:
        assert proc.stdout is not None
        return proc.stdout.readline()

    try:
        line = await asyncio.wait_for(asyncio.to_thread(wait_ready), timeout=60.0)
        assert line.strip() == "READY", f"{identity} failed to start: {line!r}"
    except BaseException:
        proc.kill()  # also EOFs the orphaned readline thread on timeout
        proc.wait(timeout=10)
        raise
    return proc


async def _spawn_replica(
    address: str, identity: str, delay_s: float, lease_ttl: float = 2.0
) -> subprocess.Popen:
    return await _spawn_worker(
        [identity, str(delay_s), str(lease_ttl), "--store", address], identity
    )


async def test_surviving_replica_adopts_killed_replicas_task(tmp_path):
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/store.sock").start()

    # record every Lease holder the store ever sees (adoption audit trail)
    holders: list[str] = []
    unsub = store.subscribe(
        lambda t, doc: holders.append(
            (doc.get("spec") or {}).get("holder_identity", "")
        ),
        kinds=frozenset({"Lease"}),
    )

    make_llm(store, name="mock-llm", provider="mock")
    make_agent(store, name="agent", llm="mock-llm")

    a = b = None
    try:
        # replica A: answers after 120s (i.e. never, within this test). Its
        # lease TTL (15s) must outlive replica B's multi-second startup so the
        # "B cannot acquire while A is live" assertion is not racy; B uses the
        # same TTL, bounding post-kill adoption latency at ~15s.
        a = await _spawn_replica(server.address, "replica-a", 120.0, lease_ttl=15.0)
        make_task(store, name="adopt-me", agent="agent", user_message="who finishes me?")

        # A must acquire the task lease and park mid-send
        lease_obj = await wait_for(
            store, "Lease", "task-llm-adopt-me", "default",
            lambda o: o.spec.holder_identity == "replica-a",
            timeout=30.0,
        )
        assert lease_obj.spec.holder_identity == "replica-a"
        task = store.get("Task", "adopt-me")
        assert task.status.phase == "ReadyForLLM"

        # replica B joins; it cannot acquire while A's lease is live
        b = await _spawn_replica(server.address, "replica-b", 0.0, lease_ttl=15.0)
        await asyncio.sleep(0.5)
        assert store.get("Lease", "task-llm-adopt-me").spec.holder_identity == "replica-a"
        assert store.get("Task", "adopt-me").status.phase == "ReadyForLLM"

        # kill the holder mid-flight (SIGKILL: no release, no cleanup)
        a.send_signal(signal.SIGKILL)
        a.wait(timeout=10)

        # B adopts after TTL expiry and finishes the task
        task = await wait_for(
            store, "Task", "adopt-me", "default",
            lambda o: o.status.phase == "FinalAnswer",
            timeout=60.0,
        )
        final = task.status.context_window[-1]
        assert final.role == "assistant"
        assert final.content == "answer from replica-b"
        assert "replica-b" in holders, f"adoption never observed; holders={holders}"
    finally:
        unsub()
        for proc in (a, b):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        server.stop()
        store.close()


async def test_store_owner_restart_under_load_measured_rto(tmp_path):
    """Kill the store OWNER (the single sqlite writer) under a 64-task load,
    restart it on the same db+socket, and require EVERY task to complete.
    The measured stall window (kill -> first post-restart progress) is
    printed so README's scaling-out section can cite a number. Reference
    anchor: an apiserver/etcd outage, which controllers ride out by
    re-list+re-watch (acp/docs/distributed-locking.md's etcd HA
    assumption) — here the follower's RemoteStore reconnect + Manager
    resync carry that contract."""
    import time

    db = str(tmp_path / "owner.db")
    address = f"unix://{tmp_path}/owner.sock"

    async def spawn_owner() -> subprocess.Popen:
        return await _spawn_worker(
            ["owner", "0.0", "2.0", "--own", db, address], "owner"
        )

    N = 64
    owner = await spawn_owner()
    follower = None
    client = None
    try:
        from agentcontrolplane_tpu.kernel import Conflict, RemoteStore

        client = RemoteStore(address, timeout=10.0, reconnect_backoff=0.1)
        # unlike the bare-store tests above, the OWNER's controllers are
        # already reconciling: our post-create status write can lose the rv
        # race — fine, the owner's controllers mark readiness themselves
        # (provider=mock needs no probe)
        try:
            make_llm(client, name="mock-llm", provider="mock")
        except Conflict:
            pass
        try:
            make_agent(client, name="agent", llm="mock-llm")
        except Conflict:
            pass
        follower = await _spawn_replica(address, "follower", 0.0, lease_ttl=2.0)

        for i in range(N):
            make_task(client, name=f"load-{i}", agent="agent", user_message=f"task {i}")

        def done_count() -> int:
            try:
                return sum(
                    1 for t in client.list("Task")
                    if t.status.phase == "FinalAnswer"
                )
            except (ConnectionError, TimeoutError):
                return -1  # owner down; count unknown

        # let the load get mid-flight (some done, most not), then kill
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            n = done_count()
            if n >= 3:
                break
            await asyncio.sleep(0.1)
        pre_kill = done_count()
        assert 0 < pre_kill < N, f"load finished too fast to test ({pre_kill}/{N})"

        t_kill = time.monotonic()
        owner.send_signal(signal.SIGKILL)
        owner.wait(timeout=10)
        await asyncio.sleep(0.5)  # a beat of real outage
        owner = await spawn_owner()

        # first post-restart progress = recovery point for the stall window
        t_progress = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            n = done_count()
            if t_progress is None and n > pre_kill:
                t_progress = time.monotonic()
            if n >= N:
                break
            await asyncio.sleep(0.1)
        final = done_count()
        assert final == N, f"only {final}/{N} tasks completed after owner restart"
        assert t_progress is not None
        stall = t_progress - t_kill
        total = time.monotonic() - t_kill
        print(f"RTO: stall_window={stall:.2f}s kill->all-done={total:.2f}s "
              f"(pre-kill {pre_kill}/{N} complete)")
        # generous bound: the point is a measured number, not a tight SLO —
        # stall covers process restart + sqlite WAL resume + reconnects
        assert stall < 60.0
    finally:
        if client is not None:
            client.close()
        for proc in (owner, follower):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


async def test_two_live_replicas_single_winner(tmp_path):
    """Both replicas race the same ReadyForLLM task; the lease admits exactly
    one send (no duplicate LLM calls, no Conflict crash on the loser)."""
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/store.sock").start()
    make_llm(store, name="mock-llm", provider="mock")
    make_agent(store, name="agent", llm="mock-llm")

    a = b = None
    try:
        a = await _spawn_replica(server.address, "replica-a", 0.3)
        b = await _spawn_replica(server.address, "replica-b", 0.3)
        make_task(store, name="race", agent="agent", user_message="go")
        task = await wait_for(
            store, "Task", "race", "default",
            lambda o: o.status.phase == "FinalAnswer",
            timeout=60.0,
        )
        answers = [m for m in task.status.context_window if m.role == "assistant"]
        # exactly one replica's answer landed, exactly once
        assert len(answers) == 1
        assert answers[0].content in ("answer from replica-a", "answer from replica-b")
    finally:
        for proc in (a, b):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        server.stop()
        store.close()
