"""Chaos: injected optimistic-concurrency conflicts and random-point operator
restarts. The reference addresses races architecturally (leases, conflict
retries, requeue) rather than with a sanitizer (SURVEY.md §4/§5); these tests
prove our equivalents hold under adversarial interleavings."""

import random

import pytest

from agentcontrolplane_tpu.api.resources import MCPTool
from agentcontrolplane_tpu.kernel import Conflict, Store, wait_for
from agentcontrolplane_tpu.llmclient import (
    MockLLMClient,
    MockLLMClientFactory,
    assistant,
    tool_call_message,
)
from agentcontrolplane_tpu.operator import Operator, OperatorOptions

from ..fixtures import make_agent, make_llm, make_mcpserver, make_task
from .test_framework import E2EMCP


class ChaosStore(Store):
    """Raises Conflict on a deterministic fraction of status updates —
    simulating a racing replica winning the write."""

    def __init__(self, backend=None, rate=0.3, seed=0):
        super().__init__(backend)
        self._chaos_rng = random.Random(seed)
        self.rate = rate
        self.armed = False  # arm after fixtures so setup is deterministic
        self.injected = 0

    def update_status(self, obj):
        if self.armed and self._chaos_rng.random() < self.rate:
            self.injected += 1
            # advance the object underneath the caller, like a racing writer
            fresh = self.try_get(obj.kind, obj.metadata.name, obj.metadata.namespace)
            if fresh is not None and fresh.metadata.resource_version == obj.metadata.resource_version:
                super().update_status(fresh)
                raise Conflict(f"chaos: injected racing write on {obj.key}")
        return super().update_status(obj)


async def test_agentic_loop_survives_injected_conflicts():
    store = ChaosStore(rate=0.3, seed=42)
    mock = MockLLMClient()
    op = Operator(
        options=OperatorOptions(enable_rest=False, llm_probe=False,
                                verify_channel_credentials=False),
        store=store,
        llm_factory=MockLLMClientFactory(mock),
    )
    op.task_reconciler.requeue_delay = 0.02
    op.toolcall_reconciler.poll_interval = 0.02
    mcp = E2EMCP(
        tools={"fetch": [MCPTool(name="fetch", description="f")]},
        results={"fetch__fetch": "fetched!"},
    )
    mcp.install(op)
    make_llm(store)
    make_mcpserver(store, "fetch")
    make_agent(store, mcp_servers=["fetch"], resolved_tools={"fetch": ["fetch"]})
    mock.script = [
        tool_call_message(("fetch__fetch", {"url": "a"})),
        assistant("all done"),
    ]
    make_task(store, user_message="go fetch")
    store.armed = True
    await op.start()
    try:
        task = await wait_for(
            store, "Task", "test-task", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=30,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "all done"
        assert store.injected > 0  # chaos actually fired
        # the conversation is still protocol-valid despite retried writes
        roles = [m.role for m in task.status.context_window]
        assert roles == ["system", "user", "assistant", "tool", "assistant"]
    finally:
        await op.stop()


@pytest.mark.parametrize("kill_after_phase", ["Initializing", "ReadyForLLM", "ToolCallsPending"])
async def test_restart_at_every_phase_resumes(tmp_path, kill_after_phase):
    """Kill the operator the moment the task reaches each phase; a fresh
    operator on the same durable store must finish the conversation."""
    from agentcontrolplane_tpu.kernel import SqliteBackend
    from agentcontrolplane_tpu.llmclient import LLMRequestError

    db = str(tmp_path / f"chaos-{kill_after_phase}.db")

    def build(scripted, hang_tools=False):
        mock = MockLLMClient()
        mock.script = list(scripted)
        op = Operator(
            options=OperatorOptions(db_path=db, enable_rest=False, llm_probe=False,
                                    verify_channel_credentials=False),
            llm_factory=MockLLMClientFactory(mock),
        )
        op.task_reconciler.requeue_delay = 0.02
        op.toolcall_reconciler.poll_interval = 0.02
        mcp = E2EMCP(
            tools={"fetch": [MCPTool(name="fetch", description="f")]},
            results={"fetch__fetch": "fetched!"},
        )
        if hang_tools:
            # first life's tool call never returns — the ToolCall dies
            # mid-execution (phase=Running), the nastiest restart point
            import asyncio as _asyncio

            async def hang(server, tool, args):
                await _asyncio.sleep(3600)

            mcp.call_tool = hang
        mcp.install(op)
        return op

    # first life: stall the LLM when we want to die in ReadyForLLM, else
    # answer with a tool call so ToolCallsPending is reachable
    first_script = (
        [LLMRequestError(503, "down")] * 500
        if kill_after_phase == "ReadyForLLM"
        else [tool_call_message(("fetch__fetch", {"url": "a"}))]
    )
    op1 = build(first_script, hang_tools=kill_after_phase == "ToolCallsPending")
    make_llm(op1.store)
    make_mcpserver(op1.store, "fetch")
    make_agent(op1.store, mcp_servers=["fetch"], resolved_tools={"fetch": ["fetch"]})
    make_task(op1.store, user_message="go")
    await op1.start()
    await wait_for(
        op1.store, "Task", "test-task", "default",
        lambda t: t.status.phase == kill_after_phase, timeout=30,
    )
    await op1.manager.stop()  # crash
    op1.store.close()

    op2 = build(
        [
            tool_call_message(("fetch__fetch", {"url": "a"})),
            assistant("recovered"),
            assistant("recovered"),
        ]
    )
    await op2.start()
    try:
        task = await wait_for(
            op2.store, "Task", "test-task", "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=30,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "recovered"
    finally:
        await op2.stop()


async def test_engine_crash_mid_task_recovers():
    """Data-plane failure recovery through the full stack: the engine loop
    dies mid-generation; the in-flight Task's LLM call fails (5xx-style,
    phase kept), the reconciler requeues, the client-side ensure_running
    rebuilds the engine, and the Task still reaches FinalAnswer."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.api import ObjectMeta
    from agentcontrolplane_tpu.api.resources import (
        LLM, BaseConfig, LLMSpec, TPUProviderConfig,
    )
    from agentcontrolplane_tpu.engine.engine import Engine
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.operator import Operator, OperatorOptions
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    from ..fixtures import make_agent, make_task, setup_with_status

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
    )
    eng.start()
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False, verify_channel_credentials=False,
            engine=eng,
        ),
    )
    op.task_reconciler.requeue_delay = 0.05
    store = op.store
    setup_with_status(
        store,
        LLM(
            metadata=ObjectMeta(name="tpu-llm"),
            spec=LLMSpec(
                provider="tpu",
                parameters=BaseConfig(model="tiny", max_tokens=8, temperature=0.0),
                tpu=TPUProviderConfig(preset="tiny"),
            ),
        ),
        lambda o: (
            setattr(o.status, "ready", True),
            setattr(o.status, "status", "Ready"),
        ),
    )
    make_agent(store, llm="tpu-llm", system="answer")

    # poison the decode program: the FIRST decode dispatch crashes the loop
    real = eng._jit_decode

    def boom(*a, **k):
        eng._jit_decode = real  # heal so the restarted engine works
        raise RuntimeError("injected decode fault")

    eng._jit_decode = boom
    make_task(store, name="crashy", user_message="hello there")
    await op.start()
    try:
        t = await wait_for(
            store, "Task", "crashy", "default",
            lambda t: t.status.phase == "FinalAnswer", timeout=120,
        )
        assert t.status.phase == "FinalAnswer"
        assert [m.role for m in t.status.context_window] == ["system", "user", "assistant"]
        # the crash actually happened (the poisoned program executed and
        # healed itself) and the task still completed
        assert eng._jit_decode is real
    finally:
        await op.stop()
        eng.stop()
