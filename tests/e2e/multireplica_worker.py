"""Operator subprocess for the multi-replica e2es (kill/adopt, owner RTO).

Runs a FULL operator (all six controllers) as one real OS process — the
topology the reference gets from N pods sharing one apiserver. Two modes:

- ``--store ADDR``: a REPLICA joining a served store over RemoteStore;
- ``--own DB ADDR``: the store OWNER — sqlite at DB, served at ADDR — so
  the owner-kill/restart RTO e2e can SIGKILL the single sqlite writer.

The LLM is a mock whose latency comes from argv, so a test can hold a
replica mid-``ReadyForLLM`` (in-flight send, task-llm lease held) long
enough to SIGKILL it.

Usage: python multireplica_worker.py <identity> <delay_s> [lease_ttl]
           (--store ADDR | --own DB ADDR)
Prints "READY" once controllers are running; serves until killed.
"""

from __future__ import annotations

import argparse
import asyncio


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("identity")
    ap.add_argument("delay_s", type=float)
    ap.add_argument("lease_ttl", nargs="?", type=float, default=2.0)
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--store", metavar="ADDR")
    mode.add_argument("--own", nargs=2, metavar=("DB", "ADDR"))
    args = ap.parse_args()

    from agentcontrolplane_tpu.llmclient import (
        MockLLMClient,
        MockLLMClientFactory,
        assistant,
    )
    from agentcontrolplane_tpu.operator import Operator, OperatorOptions

    op = Operator(
        options=OperatorOptions(
            store_address=args.store,
            db_path=args.own[0] if args.own else None,
            serve_store=args.own[1] if args.own else None,
            identity=args.identity,
            enable_rest=False,
            llm_probe=False,
            verify_channel_credentials=False,
        ),
        llm_factory=MockLLMClientFactory(
            MockLLMClient(
                default=assistant(f"answer from {args.identity}"),
                delay_s=args.delay_s,
            )
        ),
    )
    # fast cadence + short lease so adoption latency fits a test budget
    op.task_reconciler.requeue_delay = 0.05
    op.task_reconciler.lease_ttl = args.lease_ttl
    op.toolcall_reconciler.poll_interval = 0.05

    async def run() -> None:
        await op.start()
        print("READY", flush=True)
        await asyncio.Event().wait()  # until SIGKILL/SIGTERM

    asyncio.run(run())


if __name__ == "__main__":
    main()
