"""Operator replica subprocess for the two-process kill/adopt e2e.

Runs a FULL operator (all six controllers) against a RemoteStore served by
the test process — one real OS process per replica, the topology the
reference gets from N pods sharing one apiserver. The LLM is a mock whose
latency comes from argv, so the test can hold replica A mid-``ReadyForLLM``
(in-flight send, task-llm lease held) long enough to SIGKILL it.

Usage: python multireplica_worker.py <store-address> <identity> <delay_s> [lease_ttl]
Prints "READY" once controllers are running; serves until killed.
"""

from __future__ import annotations

import asyncio
import sys


def main() -> None:
    address, identity, delay_s = sys.argv[1], sys.argv[2], float(sys.argv[3])
    lease_ttl = float(sys.argv[4]) if len(sys.argv) > 4 else 2.0

    from agentcontrolplane_tpu.llmclient import (
        MockLLMClient,
        MockLLMClientFactory,
        assistant,
    )
    from agentcontrolplane_tpu.operator import Operator, OperatorOptions

    op = Operator(
        options=OperatorOptions(
            store_address=address,
            identity=identity,
            enable_rest=False,
            llm_probe=False,
            verify_channel_credentials=False,
        ),
        llm_factory=MockLLMClientFactory(
            MockLLMClient(
                default=assistant(f"answer from {identity}"), delay_s=delay_s
            )
        ),
    )
    # fast cadence + short lease so adoption latency fits a test budget
    op.task_reconciler.requeue_delay = 0.05
    op.task_reconciler.lease_ttl = lease_ttl
    op.toolcall_reconciler.poll_interval = 0.05

    async def run() -> None:
        await op.start()
        print("READY", flush=True)
        await asyncio.Event().wait()  # until SIGKILL/SIGTERM

    asyncio.run(run())


if __name__ == "__main__":
    main()
