"""Tracer coverage (ISSUE 10 satellite): the export loop, spans_for_trace,
the max_finished eviction window, the no-endpoint graceful-degradation
path, and the historical-end_time seam the flight recorder uses."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from agentcontrolplane_tpu.api.resources import SpanContext
from agentcontrolplane_tpu.observability.tracing import (
    NOOP_TRACER,
    Span,
    Tracer,
    new_span_id,
    new_trace_id,
)


class _Collector:
    """Tiny OTLP-HTTP sink capturing POSTed trace payloads."""

    def __init__(self):
        self.received: list[dict] = []
        self.event = threading.Event()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (stdlib naming)
                n = int(self.headers.get("Content-Length", 0))
                outer.received.append(json.loads(self.rfile.read(n)))
                outer.event.set()
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):  # silence the test log
                pass

        self.server = HTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self.thread.start()
        self.endpoint = f"http://127.0.0.1:{self.server.server_address[1]}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture
def collector():
    c = _Collector()
    yield c
    c.close()


def test_export_loop_posts_otlp_json(collector):
    tracer = Tracer(endpoint=collector.endpoint)
    span = tracer.start_span("Task", attributes={"task": "t1"})
    child = tracer.start_span("LLMRequest", parent=span.context())
    tracer.end_span(child)
    tracer.end_span(span, "ERROR")
    assert collector.event.wait(5.0), "export thread never delivered"
    deadline = time.monotonic() + 5.0
    while len(collector.received) < 2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(collector.received) == 2
    wire = collector.received[0]["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
    assert wire["name"] == "LLMRequest"
    assert wire["traceId"] == span.trace_id
    assert wire["parentSpanId"] == span.span_id
    assert wire["endTimeUnixNano"] >= wire["startTimeUnixNano"]


def test_no_endpoint_is_a_silent_noop():
    tracer = Tracer(endpoint="")
    span = tracer.start_span("Task")
    tracer.end_span(span)  # must not raise, must not start an export thread
    assert tracer._export_thread is None
    assert tracer.spans_for_trace(span.trace_id) == [span]


def test_unreachable_endpoint_degrades_silently():
    tracer = Tracer(endpoint="http://127.0.0.1:1")  # nothing listens there
    span = tracer.start_span("Task")
    tracer.end_span(span)
    time.sleep(0.2)  # the export thread swallows the connection error
    assert tracer.spans_for_trace(span.trace_id) == [span]


def test_spans_for_trace_filters_by_trace_id():
    tracer = Tracer(endpoint="")
    a = tracer.start_span("A")
    b = tracer.start_span("B")
    a_child = tracer.start_span("A.child", parent=a.context())
    for s in (a, b, a_child):
        tracer.end_span(s)
    got = tracer.spans_for_trace(a.trace_id)
    assert {s.name for s in got} == {"A", "A.child"}
    assert tracer.spans_for_trace(new_trace_id()) == []


def test_max_finished_eviction_window():
    tracer = Tracer(max_finished=4, endpoint="")
    spans = [tracer.start_span(f"s{i}") for i in range(8)]
    for s in spans:
        tracer.end_span(s)
    kept = list(tracer.finished)
    assert len(kept) == 4
    assert [s.name for s in kept] == ["s4", "s5", "s6", "s7"]


def test_end_span_historical_end_time():
    """The flight recorder reconstructs phase spans after the fact — both
    endpoints must be settable in the past."""
    tracer = Tracer(endpoint="")
    t0 = time.time() - 10.0
    span = Span(
        name="engine.prefill",
        trace_id=new_trace_id(),
        span_id=new_span_id(),
        parent_span_id=new_span_id(),
        start_time=t0,
    )
    tracer.end_span(span, end_time=t0 + 2.5)
    assert span.end_time == pytest.approx(t0 + 2.5)
    assert span.duration == pytest.approx(2.5)
    assert tracer.spans_for_trace(span.trace_id) == [span]


def test_parent_context_continuity():
    tracer = Tracer(endpoint="")
    root = tracer.start_span("Task")
    ctx = SpanContext(trace_id=root.trace_id, span_id=root.span_id)
    child = tracer.start_span("LLMRequest", parent=ctx)
    assert child.trace_id == root.trace_id
    assert child.parent_span_id == root.span_id
    # empty parent context starts a fresh trace
    fresh = tracer.start_span("X", parent=SpanContext(trace_id="", span_id=""))
    assert fresh.trace_id != root.trace_id and fresh.parent_span_id == ""


def test_noop_tracer_ignores_env(monkeypatch, collector):
    monkeypatch.setenv("OTEL_EXPORTER_OTLP_ENDPOINT", collector.endpoint)
    assert NOOP_TRACER.endpoint == ""  # constructed with explicit disable
    tracer = Tracer()  # a fresh default tracer DOES read the env
    assert tracer.endpoint == collector.endpoint


def test_export_queue_full_drops_instead_of_blocking(collector):
    tracer = Tracer(endpoint=collector.endpoint)
    # wedge the queue by never letting the worker drain: stuff it directly
    tracer._ensure_export_thread()
    for _ in range(2000):
        span = tracer.start_span("flood")
        tracer.end_span(span)  # queue.Full path drops silently
    # liveness is the contract: end_span never blocked; spans all finished
    assert len(tracer.finished) >= 2000 or len(tracer.finished) == tracer.finished.maxlen
