"""DispatchProfiler unit behavior (observability/profiler.py): program
aggregation, sampled device timing, the cold-compile observatory, and the
goodput/waste ledger's conservation-by-construction."""

import numpy as np
import pytest

from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.observability.profiler import (
    DispatchProfiler,
    WASTE_CAUSES,
)


def counter(name: str, **labels) -> float:
    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    return m.values.get(tuple(sorted(labels.items())), 0.0)


def _conserved(prof: DispatchProfiler) -> bool:
    led = prof.ledger()
    return led["computed"] == led["goodput"] + sum(led["waste"].values())


def test_record_aggregates_per_program_and_observes_histogram():
    prof = DispatchProfiler(enabled=True, sample_every=2)
    for _ in range(5):
        t0 = prof.start()
        prof.record("decode[slot,4x4]", t0, out=np.zeros(4),
                    real_tokens=12, padded_tokens=4, real_slots=3,
                    padded_slots=1)
    doc = prof.stats()
    p = doc["programs"]["decode[slot,4x4]"]
    assert p["dispatches"] == 5
    assert p["real_tokens"] == 60 and p["padded_tokens"] == 20
    assert p["padding_pct"] == 25.0
    assert p["real_slots"] == 15 and p["padded_slots"] == 5
    # sampling: first always blocks, then every 2nd (dispatches 0, 2, 4)
    assert p["device_samples"] == 3
    assert p["device_ms_mean"] is not None
    assert p["host_ms_mean"] >= 0.0 and p["first_wall_ms"] >= 0.0
    count, window = REGISTRY.series_window(
        "acp_engine_dispatch_seconds", {"program": "decode[slot,4x4]"}
    )
    assert count >= 5


def test_cold_compiles_only_after_mark_prewarmed():
    prof = DispatchProfiler(enabled=True)
    before = counter("acp_engine_cold_compiles_total")
    prof.record("prefill[slot,64x1]", prof.start())
    assert prof.stats()["cold_compiles"]["serving"] == 0
    assert counter("acp_engine_cold_compiles_total") == before
    prof.mark_prewarmed()
    # an already-known program stays warm
    prof.record("prefill[slot,64x1]", prof.start())
    assert prof.stats()["cold_compiles"]["serving"] == 0
    # a NEW program key after prewarm is a serving-time cold compile
    prof.record("prefill[slot,128x1]", prof.start())
    doc = prof.stats()
    assert doc["cold_compiles"]["serving"] == 1
    assert doc["cold_compiles"]["events"][0]["program"] == "prefill[slot,128x1]"
    assert doc["programs"]["prefill[slot,128x1]"]["cold"] is True
    assert doc["programs"]["prefill[slot,64x1]"]["cold"] is False
    assert counter("acp_engine_cold_compiles_total") == before + 1


def test_cold_compile_records_flight_event():
    from agentcontrolplane_tpu.observability.flight import FlightRecorder

    flight = FlightRecorder(enabled=True)
    prof = DispatchProfiler(flight=flight, enabled=True)
    prof.mark_prewarmed()
    prof.record("spill[paged,2048x4]", prof.start())
    evs = flight.events(kind="cold_compile")
    assert len(evs) == 1
    assert evs[0]["detail"]["program"] == "spill[paged,2048x4]"
    assert "wall_s" in evs[0]["detail"]


def test_ledger_conservation_by_construction_and_reclassify_zero_sum():
    prof = DispatchProfiler(enabled=True)
    prof.account(goodput=100, pad_bucket=28, prewarm=10)
    prof.account(goodput=50, pad_width=6, spec_rejected=4)
    assert _conserved(prof)
    led = prof.ledger()
    assert led["computed"] == 198 and led["goodput"] == 150
    prof.reclassify("preempt_discard", 40)
    assert _conserved(prof)
    led = prof.ledger()
    assert led["goodput"] == 110 and led["waste"]["preempt_discard"] == 40
    # clamp: reclassifying more than the available goodput stays zero-sum
    prof.reclassify("dedup_rewind", 10_000)
    assert _conserved(prof)
    led = prof.ledger()
    assert led["goodput"] == 0 and led["waste"]["dedup_rewind"] == 110
    # zero/negative reclassify is a no-op
    prof.reclassify("swap_recompute", 0)
    prof.reclassify("swap_recompute", -5)
    assert _conserved(prof)


def test_unknown_waste_cause_raises():
    prof = DispatchProfiler(enabled=True)
    with pytest.raises(KeyError):
        prof.account(goodput=1, bogus_cause=2)
    prof.account(goodput=1)
    with pytest.raises(KeyError):
        prof.reclassify("bogus_cause", 1)
    assert set(prof.ledger()["waste"]) == set(WASTE_CAUSES)


def test_publish_pushes_delta_counters_and_ratio_gauge():
    prof = DispatchProfiler(enabled=True)
    base_good = counter("acp_engine_tokens_computed_total", cause="goodput")
    base_pad = counter("acp_engine_tokens_computed_total", cause="pad_bucket")
    prof.account(goodput=30, pad_bucket=10)
    prof.publish()
    assert counter("acp_engine_tokens_computed_total", cause="goodput") == base_good + 30
    assert counter("acp_engine_tokens_computed_total", cause="pad_bucket") == base_pad + 10
    # delta-based: a second publish with no new activity adds nothing
    prof.publish()
    assert counter("acp_engine_tokens_computed_total", cause="goodput") == base_good + 30
    assert counter("acp_engine_goodput_ratio") == pytest.approx(0.75)
    # per-program token split publishes too
    prof.record("chunk[slot,32x2]", prof.start(), real_tokens=40, padded_tokens=24)
    base_real = counter(
        "acp_engine_dispatch_tokens_total", program="chunk[slot,32x2]", kind="real"
    )
    prof.publish()
    assert counter(
        "acp_engine_dispatch_tokens_total", program="chunk[slot,32x2]", kind="real"
    ) == base_real + 40


def test_disabled_profiler_is_inert():
    prof = DispatchProfiler(enabled=False)
    assert prof.start() == 0.0
    prof.record("decode[slot,1x4]", 0.0, real_tokens=4)
    prof.account(goodput=10, pad_width=2)
    prof.reclassify("preempt_discard", 5)
    prof.publish()
    doc = prof.stats()
    assert doc["enabled"] is False
    assert doc["programs"] == {}
    assert doc["goodput"]["computed"] == 0
    assert doc["goodput"]["ratio"] == 1.0


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("ACP_PROF", "0")
    assert DispatchProfiler().enabled is False
    monkeypatch.setenv("ACP_PROF", "1")
    monkeypatch.setenv("ACP_PROF_SAMPLE", "7")
    prof = DispatchProfiler()
    assert prof.enabled is True and prof.sample_every == 7
