"""Prometheus text-exposition escaping (ISSUE 10 satellite): label values
containing ``\\``, ``"`` or newlines must render escaped per the text
format spec — an unescaped model name or fault label corrupts the whole
scrape (every series after it fails to parse)."""

from agentcontrolplane_tpu.observability.metrics import Registry


def _line_for(reg: Registry, name: str) -> str:
    lines = [ln for ln in reg.render().splitlines() if ln.startswith(name + "{")]
    assert len(lines) == 1, lines
    return lines[0]


def test_label_values_escape_backslash_quote_and_newline():
    reg = Registry()
    reg.gauge_set(
        "acp_test_gauge", 1.0,
        labels={"model": 'pa\\th"quoted"\nline2'},
    )
    line = _line_for(reg, "acp_test_gauge")
    # escaped per spec: backslash first, then quote, then newline
    assert '\\\\' in line and '\\"' in line and "\\n" in line
    assert "\n" not in line  # one physical line — nothing raw leaked
    assert line == 'acp_test_gauge{model="pa\\\\th\\"quoted\\"\\nline2"} 1.0'


def test_histogram_series_labels_escaped_too():
    reg = Registry()
    reg.observe("acp_test_hist", 0.5, labels={"phase": 'pre"fill\n'})
    rendered = reg.render()
    for ln in rendered.splitlines():
        if ln.startswith("acp_test_hist"):
            assert '"pre\\"fill\\n"' in ln


def test_help_text_newline_and_backslash_escaped():
    reg = Registry()
    reg.counter_add("acp_test_total", 1.0, help="line1\nline2 \\ tail")
    help_lines = [
        ln for ln in reg.render().splitlines() if ln.startswith("# HELP acp_test_total")
    ]
    assert help_lines == ["# HELP acp_test_total line1\\nline2 \\\\ tail"]


def test_plain_values_unchanged():
    reg = Registry()
    reg.gauge_set("acp_plain", 2.0, labels={"kind": "Task", "phase": "Ready"})
    assert _line_for(reg, "acp_plain") == 'acp_plain{kind="Task",phase="Ready"} 2.0'


def test_scrape_stays_parseable_with_hostile_value():
    """Every rendered line must still look like `name{labels} value` or a
    comment — the corruption mode the escaping prevents is a label value
    splitting one sample across physical lines."""
    reg = Registry()
    reg.gauge_set("acp_a", 1.0, labels={"v": 'x\n" 666\nacp_fake 1'})
    reg.gauge_set("acp_b", 2.0)
    lines = reg.render().strip().splitlines()
    assert len(lines) == 4  # 2 TYPE comments + 2 samples
    samples = [ln for ln in lines if not ln.startswith("#")]
    assert len(samples) == 2
    for ln in samples:
        assert ln.rsplit(" ", 1)[1] in ("1.0", "2.0")
    assert not any(ln.startswith("acp_fake") for ln in lines)
