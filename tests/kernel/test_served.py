"""Served store: the cross-process kernel (kernel/served.py).

The reference's store is the kube-apiserver — N operator pods share it over
the network, which is what makes Lease adoption meaningful across processes
(acp/docs/distributed-locking.md:84-150). These tests drive StoreServer +
RemoteStore in one process over real sockets; the true two-OS-process
kill/adopt scenario lives in tests/e2e/test_multireplica.py.
"""

from __future__ import annotations

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import Task, TaskSpec, LocalObjectRef
from agentcontrolplane_tpu.kernel import (
    AlreadyExists,
    Conflict,
    NotFound,
    RemoteStore,
    Store,
    StoreServer,
    lease,
)


@pytest.fixture
def served(tmp_path):
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/store.sock").start()
    remotes: list[RemoteStore] = []

    def connect() -> RemoteStore:
        r = RemoteStore(server.address, timeout=10.0)
        remotes.append(r)
        return r

    yield store, connect
    for r in remotes:
        r.close()
    server.stop()


def _task(name: str, labels=None) -> Task:
    return Task(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=TaskSpec(agent_ref=LocalObjectRef(name="a"), user_message="hi"),
    )


def test_crud_round_trip(served):
    _, connect = served
    remote = connect()
    created = remote.create(_task("t1"))
    assert created.metadata.resource_version > 0

    got = remote.get("Task", "t1")
    assert got.spec.user_message == "hi"

    got.status.phase = "Initializing"
    updated = remote.update_status(got)
    assert updated.status.phase == "Initializing"
    assert updated.metadata.resource_version > got.metadata.resource_version

    remote.delete("Task", "t1")
    assert remote.try_get("Task", "t1") is None


def test_error_mapping(served):
    _, connect = served
    remote = connect()
    with pytest.raises(NotFound):
        remote.get("Task", "missing")
    remote.create(_task("dup"))
    with pytest.raises(AlreadyExists):
        remote.create(_task("dup"))
    stale = remote.get("Task", "dup")
    remote.update_status(remote.get("Task", "dup"))  # bump rv
    with pytest.raises(Conflict):
        remote.update_status(stale)


def test_mutations_visible_across_clients(served):
    """Two RemoteStores = two replicas sharing one store: a write through one
    is immediately readable through the other (single source of truth)."""
    _, connect = served
    a, b = connect(), connect()
    a.create(_task("shared"))
    got = b.get("Task", "shared")
    got.status.phase = "ReadyForLLM"
    b.update_status(got)
    assert a.get("Task", "shared").status.phase == "ReadyForLLM"


def test_list_with_label_selector(served):
    _, connect = served
    remote = connect()
    remote.create(_task("t1", labels={"acp.tpu/task": "parent"}))
    remote.create(_task("t2", labels={"acp.tpu/task": "other"}))
    out = remote.list("Task", label_selector={"acp.tpu/task": "parent"})
    assert [o.metadata.name for o in out] == ["t1"]
    assert len(remote.list("Task")) == 2


def test_precondition_delete_conflict(served):
    _, connect = served
    remote = connect()
    remote.create(_task("t1"))
    old_rv = remote.get("Task", "t1").metadata.resource_version
    remote.update_status(remote.get("Task", "t1"))
    with pytest.raises(Conflict):
        remote.delete("Task", "t1", resource_version=old_rv)


def test_phase_counts(served):
    _, connect = served
    remote = connect()
    remote.create(_task("t1"))
    obj = remote.get("Task", "t1")
    obj.status.phase = "FinalAnswer"
    remote.update_status(obj)
    counts = remote.phase_counts()
    assert counts[("Task", "FinalAnswer")] == 1


async def test_watch_streams_to_remote_client(served):
    local, connect = served
    remote = connect()
    watch = remote.watch("Task")
    local.create(_task("t1"))  # mutation on the server side
    ev = await watch.next(timeout=5.0)
    assert ev is not None and ev.type == "ADDED" and ev.object.metadata.name == "t1"

    other = connect()
    obj = other.get("Task", "t1")
    obj.status.phase = "Initializing"
    other.update_status(obj)  # mutation via a DIFFERENT client
    ev = await watch.next(timeout=5.0)
    assert ev is not None and ev.type == "MODIFIED"
    assert ev.object.status.phase == "Initializing"

    watch.stop()
    assert await watch.next(timeout=1.0) is None


async def test_watch_kind_filter(served):
    _, connect = served
    remote = connect()
    watch = remote.watch("Lease")
    remote.create(_task("noise"))
    lease.try_acquire(remote, "task-llm-x", "pod-a")
    ev = await watch.next(timeout=5.0)
    assert ev is not None and ev.object.kind == "Lease"
    watch.stop()


def test_cross_client_lease_contention(served):
    """The headline property: leases over RemoteStores behave like the
    reference's Lease CRs over the apiserver — one winner, adoption only
    after expiry (state_machine.go:1069-1132)."""
    _, connect = served
    a, b = connect(), connect()
    assert lease.try_acquire(a, "task-llm-t1", "pod-a", ttl=30, now=100.0)
    assert not lease.try_acquire(b, "task-llm-t1", "pod-b", ttl=30, now=110.0)
    # pod-a dies; pod-b adopts after TTL expiry
    assert lease.try_acquire(b, "task-llm-t1", "pod-b", ttl=30, now=131.0)
    assert a.get("Lease", "task-llm-t1").spec.holder_identity == "pod-b"


def test_remote_store_survives_server_restart_of_client(served):
    """Closing one client must not disturb the others."""
    _, connect = served
    a, b = connect(), connect()
    a.create(_task("t1"))
    a.close()
    assert b.get("Task", "t1").metadata.name == "t1"


def test_closed_connection_raises_connection_error(served):
    _, connect = served
    remote = connect()
    remote.close()
    with pytest.raises((ConnectionError, OSError)):
        remote.get("Task", "anything")


def test_store_token_handshake(tmp_path):
    """The served socket carries Secrets and Lease writes, so with a server
    token: right token = full API, wrong token = refused without retry,
    no token = one error reply then the connection is dropped."""
    import os as _os

    from agentcontrolplane_tpu.kernel import StoreAuthError

    store = Store()
    path = f"{tmp_path}/auth.sock"
    server = StoreServer(store, f"unix://{path}", token="s3cret").start()
    try:
        assert (_os.stat(path).st_mode & 0o777) == 0o600  # owner-only socket

        ok = RemoteStore(server.address, timeout=5.0, token="s3cret")
        ok.create(_task("t1"))
        assert ok.get("Task", "t1").metadata.name == "t1"
        ok.close()

        with pytest.raises(StoreAuthError):
            RemoteStore(server.address, timeout=5.0, token="wrong")

        anon = RemoteStore(server.address, timeout=5.0)
        with pytest.raises((StoreAuthError, ConnectionError, TimeoutError)):
            anon.get("Task", "t1")
        anon.close()
    finally:
        server.stop()


async def test_store_token_watch_streams(tmp_path):
    """Watches work over an authenticated connection, including after the
    reconnect path re-runs the handshake."""
    address = f"unix://{tmp_path}/authwatch.sock"
    store = Store()
    server = StoreServer(store, address, token="tok").start()
    remote = RemoteStore(address, timeout=10.0, reconnect_backoff=0.05, token="tok")
    try:
        w = remote.watch("Task")
        store.create(_task("t1"))
        ev = await w.next(timeout=5.0)
        assert ev is not None and ev.object.metadata.name == "t1"

        server.stop()
        assert await w.next(timeout=5.0) is None  # sentinel
        server = StoreServer(store, address, token="tok").start()

        w2 = remote.watch("Task")  # reconnects + re-authenticates
        store.create(_task("t2"))
        ev = await w2.next(timeout=5.0)
        assert ev is not None and ev.object.metadata.name == "t2"
        w2.stop()
    finally:
        remote.close()
        server.stop()


def test_auth_handshake_eof_is_retryable_not_rejection(tmp_path):
    """A server that accepts the connection but dies before replying to the
    auth op (owner restarting — the RTO scenario) must surface as a
    ConnectionError, NOT StoreAuthError: auth errors abort the reconnect
    backoff, and blaming a correct token for a transport failure would
    strand the replica."""
    import socket as sk
    import threading

    from agentcontrolplane_tpu.kernel import StoreAuthError

    lst = sk.socket(sk.AF_UNIX, sk.SOCK_STREAM)
    path = f"{tmp_path}/eof.sock"
    lst.bind(path)
    lst.listen(1)

    def accept_and_slam():
        conn, _ = lst.accept()
        conn.recv(4096)  # swallow the hello, reply with nothing
        conn.close()

    t = threading.Thread(target=accept_and_slam, daemon=True)
    t.start()
    try:
        with pytest.raises(ConnectionError) as exc:
            RemoteStore(f"unix://{path}", timeout=5.0, token="right-token")
        assert not isinstance(exc.value, StoreAuthError)
    finally:
        lst.close()


def test_tokenless_server_accepts_token_client(tmp_path):
    """Rolling a token out: a client already configured with the secret can
    still talk to a replica that has not restarted with one yet."""
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/mixed.sock").start()
    try:
        remote = RemoteStore(server.address, timeout=5.0, token="early")
        remote.create(_task("t1"))
        assert store.get("Task", "t1").metadata.name == "t1"
        remote.close()
    finally:
        server.stop()


def test_tcp_transport(tmp_path):
    store = Store()
    server = StoreServer(store, "tcp://127.0.0.1:0").start()
    try:
        assert server.address.startswith("tcp://127.0.0.1:")
        remote = RemoteStore(server.address, timeout=10.0)
        remote.create(_task("t1"))
        assert store.get("Task", "t1").metadata.name == "t1"
        remote.close()
    finally:
        server.stop()


async def test_remote_store_reconnects_after_server_restart(tmp_path):
    """Owner-pod restart: RPC ops lazily reconnect (watches end and are
    re-established by consumers) — a follower must not go deaf forever."""
    address = f"unix://{tmp_path}/restart.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0)
    try:
        remote.create(_task("t1"))
        watch = remote.watch("Task")

        server.stop()
        # the dead connection ends the watch with a sentinel...
        assert await watch.next(timeout=5.0) is None

        # ...and a restarted owner (same durable state) is picked up
        # transparently by the next RPC
        server = StoreServer(store, address).start()
        assert remote.get("Task", "t1").metadata.name == "t1"
        remote.create(_task("t2"))
        assert store.get("Task", "t2").metadata.name == "t2"

        # re-watching after reconnect streams again
        watch2 = remote.watch("Task")
        store.create(_task("t3"))
        ev = await watch2.next(timeout=5.0)
        assert ev is not None and ev.object.metadata.name == "t3"
        watch2.stop()
    finally:
        remote.close()
        server.stop()


async def test_remote_store_close_disables_reconnect(tmp_path):
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/c.sock").start()
    remote = RemoteStore(server.address, timeout=5.0)
    try:
        remote.close()
        with pytest.raises((ConnectionError, OSError)):
            remote.get("Task", "anything")
    finally:
        server.stop()


async def test_first_rewatch_after_restart_is_not_deaf(tmp_path):
    """When watch() is the FIRST RPC after the store owner dies, its own
    _call performs the reconnect. The old reconnect path cleared the just-
    registered handle, so the server streamed events the client silently
    dropped and no sentinel ever arrived — the first re-established watch
    was permanently deaf. It must stream."""
    address = f"unix://{tmp_path}/deaf.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0, reconnect_backoff=0.05)
    try:
        remote.create(_task("t1"))
        w0 = remote.watch("Task")
        server.stop()
        # sentinel proves the reader died and _closed is set, so the next
        # watch() really is the call that reconnects
        assert await w0.next(timeout=5.0) is None
        server = StoreServer(store, address).start()

        w1 = remote.watch("Task")
        store.create(_task("t2"))
        ev = await w1.next(timeout=5.0)
        assert ev is not None and ev.object.metadata.name == "t2"
        w1.stop()
    finally:
        remote.close()
        server.stop()


async def test_reconnect_prunes_only_stale_epoch_watches(tmp_path):
    """The reconnect prune must be epoch-scoped: a handle stamped for the
    NEW connection (a concurrent watch() racing the reconnect) survives,
    while handles that rode the dead connection are dropped."""
    import asyncio

    from agentcontrolplane_tpu.kernel.served import _RemoteWatch

    address = f"unix://{tmp_path}/prune.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0, reconnect_backoff=0.05)
    try:
        w_old = remote.watch("Task")
        server.stop()
        for _ in range(100):
            if remote._closed.is_set():
                break
            await asyncio.sleep(0.05)
        assert remote._closed.is_set()
        server = StoreServer(store, address).start()

        future_handle = _RemoteWatch(remote, 999)
        future_handle._epoch = remote._conn_epoch + 1
        remote._watches[999] = future_handle
        # a handle that rode the dead connection but was registered after
        # the reader's cleanup ran: ONLY the prune can end it
        stale_handle = _RemoteWatch(remote, 998)
        stale_handle._epoch = remote._conn_epoch
        remote._watches[998] = stale_handle

        assert remote.ping()  # triggers the reconnect + prune
        assert 999 in remote._watches, "future-epoch handle must survive"
        assert w_old.wid not in remote._watches, "dead-conn handle pruned"
        assert 998 not in remote._watches
        # the prune itself must deliver the end marker — a pruned-but-never-
        # ended watch would hang its consumer forever
        assert stale_handle.queue.qsize() == 1
        assert await stale_handle.next(timeout=1.0) is None
        w_old.stop()
    finally:
        remote.close()
        server.stop()


async def test_stale_end_marker_does_not_end_realigned_watch(tmp_path):
    """A watch whose subscribe rode a NEWER connection than a queued end
    marker must skip the marker and keep streaming (the marker belongs to a
    connection the handle outlived)."""
    from agentcontrolplane_tpu.kernel.served import _EndOfWatch

    address = f"unix://{tmp_path}/stale.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0)
    try:
        w = remote.watch("Task")
        w._deliver(_EndOfWatch(w._epoch - 1))  # stale: from an older epoch
        store.create(_task("t1"))
        ev = await w.next(timeout=5.0)
        assert ev is not None and ev.object.metadata.name == "t1"
        # a current-epoch marker still ends it
        w._deliver(_EndOfWatch(w._epoch))
        assert await w.next(timeout=5.0) is None
        w.stop()
    finally:
        remote.close()
        server.stop()


async def test_manager_watch_loop_resyncs_after_server_restart(tmp_path):
    """A follower's controller manager re-lists + re-watches when the
    served-store connection dies (the apiserver watch contract), so
    objects created during/after the outage still get reconciled."""
    import asyncio

    from agentcontrolplane_tpu.kernel import Manager, Result

    address = f"unix://{tmp_path}/resync.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0, reconnect_backoff=0.05)

    seen: set[str] = set()

    class Toy:
        async def reconcile(self, key):
            seen.add(key[2])
            return Result.done()

    mgr = Manager(remote)
    mgr.add_controller("toy", "Task", Toy(), workers=1)
    await mgr.start()
    try:
        store.create(_task("before"))
        for _ in range(100):
            if "before" in seen:
                break
            await asyncio.sleep(0.05)
        assert "before" in seen

        server.stop()
        await asyncio.sleep(0.2)  # watch dies; loop enters resync retries
        store.create(_task("during-outage"))
        server = StoreServer(store, address).start()

        for _ in range(200):
            if "during-outage" in seen:
                break
            await asyncio.sleep(0.05)
        assert "during-outage" in seen, "resync never recovered the watch"
    finally:
        await mgr.stop()
        remote.close()
        server.stop()


def test_unix_socket_born_owner_only(tmp_path, monkeypatch):
    """The store socket grants full control-plane read/write (Secrets
    included): it must never exist with umask-default permissions, even
    for the instant between bind() and the post-bind chmod. The bind runs
    under umask 0o177 so the inode is BORN 0600 — asserted by capturing
    the effective umask inside bind itself."""
    import os
    import socket as socket_mod
    import stat

    seen: dict = {}
    real_bind = socket_mod.socket.bind

    def spying_bind(self, addr):
        if isinstance(addr, str):  # the unix path bind, not TCP
            cur = os.umask(0)
            os.umask(cur)
            seen["umask"] = cur
        return real_bind(self, addr)

    monkeypatch.setattr(socket_mod.socket, "bind", spying_bind)
    # a permissive ambient umask must not leak into the socket's birth mode
    old = os.umask(0o000)
    try:
        store = Store()
        path = f"{tmp_path}/born.sock"
        server = StoreServer(store, f"unix://{path}").start()
        try:
            assert seen["umask"] == 0o177
            mode = stat.S_IMODE(os.stat(path).st_mode)
            assert mode == 0o600
            # the narrowed umask was scoped to the bind, not left installed
            cur = os.umask(0)
            os.umask(cur)
            assert cur == 0o000
        finally:
            server.stop()
    finally:
        os.umask(old)
