"""Served store: the cross-process kernel (kernel/served.py).

The reference's store is the kube-apiserver — N operator pods share it over
the network, which is what makes Lease adoption meaningful across processes
(acp/docs/distributed-locking.md:84-150). These tests drive StoreServer +
RemoteStore in one process over real sockets; the true two-OS-process
kill/adopt scenario lives in tests/e2e/test_multireplica.py.
"""

from __future__ import annotations

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import Task, TaskSpec, LocalObjectRef
from agentcontrolplane_tpu.kernel import (
    AlreadyExists,
    Conflict,
    NotFound,
    RemoteStore,
    Store,
    StoreServer,
    lease,
)


@pytest.fixture
def served(tmp_path):
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/store.sock").start()
    remotes: list[RemoteStore] = []

    def connect() -> RemoteStore:
        r = RemoteStore(server.address, timeout=10.0)
        remotes.append(r)
        return r

    yield store, connect
    for r in remotes:
        r.close()
    server.stop()


def _task(name: str, labels=None) -> Task:
    return Task(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=TaskSpec(agent_ref=LocalObjectRef(name="a"), user_message="hi"),
    )


def test_crud_round_trip(served):
    _, connect = served
    remote = connect()
    created = remote.create(_task("t1"))
    assert created.metadata.resource_version > 0

    got = remote.get("Task", "t1")
    assert got.spec.user_message == "hi"

    got.status.phase = "Initializing"
    updated = remote.update_status(got)
    assert updated.status.phase == "Initializing"
    assert updated.metadata.resource_version > got.metadata.resource_version

    remote.delete("Task", "t1")
    assert remote.try_get("Task", "t1") is None


def test_error_mapping(served):
    _, connect = served
    remote = connect()
    with pytest.raises(NotFound):
        remote.get("Task", "missing")
    remote.create(_task("dup"))
    with pytest.raises(AlreadyExists):
        remote.create(_task("dup"))
    stale = remote.get("Task", "dup")
    remote.update_status(remote.get("Task", "dup"))  # bump rv
    with pytest.raises(Conflict):
        remote.update_status(stale)


def test_mutations_visible_across_clients(served):
    """Two RemoteStores = two replicas sharing one store: a write through one
    is immediately readable through the other (single source of truth)."""
    _, connect = served
    a, b = connect(), connect()
    a.create(_task("shared"))
    got = b.get("Task", "shared")
    got.status.phase = "ReadyForLLM"
    b.update_status(got)
    assert a.get("Task", "shared").status.phase == "ReadyForLLM"


def test_list_with_label_selector(served):
    _, connect = served
    remote = connect()
    remote.create(_task("t1", labels={"acp.tpu/task": "parent"}))
    remote.create(_task("t2", labels={"acp.tpu/task": "other"}))
    out = remote.list("Task", label_selector={"acp.tpu/task": "parent"})
    assert [o.metadata.name for o in out] == ["t1"]
    assert len(remote.list("Task")) == 2


def test_precondition_delete_conflict(served):
    _, connect = served
    remote = connect()
    remote.create(_task("t1"))
    old_rv = remote.get("Task", "t1").metadata.resource_version
    remote.update_status(remote.get("Task", "t1"))
    with pytest.raises(Conflict):
        remote.delete("Task", "t1", resource_version=old_rv)


def test_phase_counts(served):
    _, connect = served
    remote = connect()
    remote.create(_task("t1"))
    obj = remote.get("Task", "t1")
    obj.status.phase = "FinalAnswer"
    remote.update_status(obj)
    counts = remote.phase_counts()
    assert counts[("Task", "FinalAnswer")] == 1


async def test_watch_streams_to_remote_client(served):
    local, connect = served
    remote = connect()
    watch = remote.watch("Task")
    local.create(_task("t1"))  # mutation on the server side
    ev = await watch.next(timeout=5.0)
    assert ev is not None and ev.type == "ADDED" and ev.object.metadata.name == "t1"

    other = connect()
    obj = other.get("Task", "t1")
    obj.status.phase = "Initializing"
    other.update_status(obj)  # mutation via a DIFFERENT client
    ev = await watch.next(timeout=5.0)
    assert ev is not None and ev.type == "MODIFIED"
    assert ev.object.status.phase == "Initializing"

    watch.stop()
    assert await watch.next(timeout=1.0) is None


async def test_watch_kind_filter(served):
    _, connect = served
    remote = connect()
    watch = remote.watch("Lease")
    remote.create(_task("noise"))
    lease.try_acquire(remote, "task-llm-x", "pod-a")
    ev = await watch.next(timeout=5.0)
    assert ev is not None and ev.object.kind == "Lease"
    watch.stop()


def test_cross_client_lease_contention(served):
    """The headline property: leases over RemoteStores behave like the
    reference's Lease CRs over the apiserver — one winner, adoption only
    after expiry (state_machine.go:1069-1132)."""
    _, connect = served
    a, b = connect(), connect()
    assert lease.try_acquire(a, "task-llm-t1", "pod-a", ttl=30, now=100.0)
    assert not lease.try_acquire(b, "task-llm-t1", "pod-b", ttl=30, now=110.0)
    # pod-a dies; pod-b adopts after TTL expiry
    assert lease.try_acquire(b, "task-llm-t1", "pod-b", ttl=30, now=131.0)
    assert a.get("Lease", "task-llm-t1").spec.holder_identity == "pod-b"


def test_remote_store_survives_server_restart_of_client(served):
    """Closing one client must not disturb the others."""
    _, connect = served
    a, b = connect(), connect()
    a.create(_task("t1"))
    a.close()
    assert b.get("Task", "t1").metadata.name == "t1"


def test_closed_connection_raises_connection_error(served):
    _, connect = served
    remote = connect()
    remote.close()
    with pytest.raises((ConnectionError, OSError)):
        remote.get("Task", "anything")


def test_tcp_transport(tmp_path):
    store = Store()
    server = StoreServer(store, "tcp://127.0.0.1:0").start()
    try:
        assert server.address.startswith("tcp://127.0.0.1:")
        remote = RemoteStore(server.address, timeout=10.0)
        remote.create(_task("t1"))
        assert store.get("Task", "t1").metadata.name == "t1"
        remote.close()
    finally:
        server.stop()


async def test_remote_store_reconnects_after_server_restart(tmp_path):
    """Owner-pod restart: RPC ops lazily reconnect (watches end and are
    re-established by consumers) — a follower must not go deaf forever."""
    address = f"unix://{tmp_path}/restart.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0)
    try:
        remote.create(_task("t1"))
        watch = remote.watch("Task")

        server.stop()
        # the dead connection ends the watch with a sentinel...
        assert await watch.next(timeout=5.0) is None

        # ...and a restarted owner (same durable state) is picked up
        # transparently by the next RPC
        server = StoreServer(store, address).start()
        assert remote.get("Task", "t1").metadata.name == "t1"
        remote.create(_task("t2"))
        assert store.get("Task", "t2").metadata.name == "t2"

        # re-watching after reconnect streams again
        watch2 = remote.watch("Task")
        store.create(_task("t3"))
        ev = await watch2.next(timeout=5.0)
        assert ev is not None and ev.object.metadata.name == "t3"
        watch2.stop()
    finally:
        remote.close()
        server.stop()


async def test_remote_store_close_disables_reconnect(tmp_path):
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/c.sock").start()
    remote = RemoteStore(server.address, timeout=5.0)
    try:
        remote.close()
        with pytest.raises((ConnectionError, OSError)):
            remote.get("Task", "anything")
    finally:
        server.stop()


async def test_manager_watch_loop_resyncs_after_server_restart(tmp_path):
    """A follower's controller manager re-lists + re-watches when the
    served-store connection dies (the apiserver watch contract), so
    objects created during/after the outage still get reconciled."""
    import asyncio

    from agentcontrolplane_tpu.kernel import Manager, Result

    address = f"unix://{tmp_path}/resync.sock"
    store = Store()
    server = StoreServer(store, address).start()
    remote = RemoteStore(address, timeout=10.0, reconnect_backoff=0.05)

    seen: set[str] = set()

    class Toy:
        async def reconcile(self, key):
            seen.add(key[2])
            return Result.done()

    mgr = Manager(remote)
    mgr.add_controller("toy", "Task", Toy(), workers=1)
    await mgr.start()
    try:
        store.create(_task("before"))
        for _ in range(100):
            if "before" in seen:
                break
            await asyncio.sleep(0.05)
        assert "before" in seen

        server.stop()
        await asyncio.sleep(0.2)  # watch dies; loop enters resync retries
        store.create(_task("during-outage"))
        server = StoreServer(store, address).start()

        for _ in range(200):
            if "during-outage" in seen:
                break
            await asyncio.sleep(0.05)
        assert "during-outage" in seen, "resync never recovered the watch"
    finally:
        await mgr.stop()
        remote.close()
        server.stop()
