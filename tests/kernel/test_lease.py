"""Lease semantics: create-or-adopt-expired, per the reference's
acquireTaskLease (task/state_machine.go:1069-1132) and
acp/docs/distributed-locking.md expiry/takeover scenarios."""

from agentcontrolplane_tpu.kernel import Store, lease


def test_acquire_create_and_renew(store):
    assert lease.try_acquire(store, "task-llm-t1", "pod-a", ttl=30, now=100.0)
    # held by us -> renew succeeds
    assert lease.try_acquire(store, "task-llm-t1", "pod-a", ttl=30, now=110.0)
    got = store.get("Lease", "task-llm-t1")
    assert got.spec.holder_identity == "pod-a"
    assert got.spec.renew_time == 110.0
    assert got.spec.acquire_time == 100.0


def test_contention_live_lease_not_acquired(store):
    assert lease.try_acquire(store, "l", "pod-a", ttl=30, now=100.0)
    assert not lease.try_acquire(store, "l", "pod-b", ttl=30, now=110.0)
    assert store.get("Lease", "l").spec.holder_identity == "pod-a"


def test_expired_lease_adopted(store):
    """A surviving replica adopts a dead replica's lock after TTL expiry."""
    assert lease.try_acquire(store, "l", "pod-a", ttl=30, now=100.0)
    assert lease.try_acquire(store, "l", "pod-b", ttl=30, now=131.0)
    got = store.get("Lease", "l")
    assert got.spec.holder_identity == "pod-b"
    assert got.spec.acquire_time == 131.0


def test_release_only_by_holder(store):
    lease.try_acquire(store, "l", "pod-a", ttl=30, now=100.0)
    lease.release(store, "l", "pod-b")
    assert store.get("Lease", "l").spec.holder_identity == "pod-a"
    lease.release(store, "l", "pod-a")
    # released = holder cleared but the object KEPT: deleting would reset
    # the epoch and let a pre-deposition fencing token validate again
    released = store.get("Lease", "l")
    assert released.spec.holder_identity == ""
    assert released.spec.epoch == 1
    lease.release(store, "l", "pod-a")  # idempotent
    # the next acquisition (any holder) adopts immediately at a HIGHER epoch
    assert lease.try_acquire_epoch(store, "l", "pod-b", ttl=30, now=101.0) == 2


def test_release_does_not_delete_adopted_lease(store):
    """rv-guarded release: holder A outlives its TTL, B adopts the expired
    lease, then A's deferred release must NOT delete B's lease (it would
    let a third replica acquire while B's work is in flight)."""
    lease.try_acquire(store, "l", "pod-a", ttl=30, now=100.0)
    stale = store.get("Lease", "l")  # what pod-a would observe pre-release
    assert lease.try_acquire(store, "l", "pod-b", ttl=30, now=131.0)  # adopt

    # simulate pod-a's get-then-delete racing the adoption: the precondition
    # delete with the stale rv must be refused
    from agentcontrolplane_tpu.kernel.errors import Conflict

    try:
        store.delete("Lease", "l", resource_version=stale.metadata.resource_version)
        raised = False
    except Conflict:
        raised = True
    assert raised
    assert store.get("Lease", "l").spec.holder_identity == "pod-b"

    # and the release() helper itself (re-gets, sees holder b) is a no-op
    lease.release(store, "l", "pod-a")
    assert store.get("Lease", "l").spec.holder_identity == "pod-b"
