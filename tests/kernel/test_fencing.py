"""Fencing tokens for leader-gated writes (kernel/store.py _check_fence,
kernel/lease.py try_acquire_epoch, kernel/runtime.py LeaderElector.fence).

The reference's leader election (acp/cmd/main.go:213-226) has the same
deposed-leader exposure controller-runtime's default election has; here the
store itself rejects a stale leader's writes: the election lease carries an
epoch bumped on every change of holder, leader-gated mutations carry
(holder, epoch), and the check is atomic with the write under the store
lock.
"""

from __future__ import annotations

import asyncio

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import Task, TaskSpec, LocalObjectRef
from agentcontrolplane_tpu.kernel import (
    Conflict,
    FencedStore,
    LeaderElector,
    RemoteStore,
    Store,
    StoreServer,
    lease,
)

LEASE = "acp-tpu-leader"


def _task(name: str) -> Task:
    return Task(
        metadata=ObjectMeta(name=name),
        spec=TaskSpec(agent_ref=LocalObjectRef(name="a"), userMessage="hi"),
    )


def _fence(holder: str, epoch: int) -> dict:
    return {"name": LEASE, "namespace": "default", "holder": holder, "epoch": epoch}


def test_epoch_bumps_on_takeover_not_renewal():
    store = Store()
    assert lease.try_acquire_epoch(store, LEASE, "A", ttl=10.0, now=100.0) == 1
    # renewal by the same holder keeps the epoch
    assert lease.try_acquire_epoch(store, LEASE, "A", ttl=10.0, now=105.0) == 1
    # a live lease resists takeover
    assert lease.try_acquire_epoch(store, LEASE, "B", ttl=10.0, now=106.0) is None
    # adoption after expiry bumps the epoch
    assert lease.try_acquire_epoch(store, LEASE, "B", ttl=10.0, now=120.0) == 2
    # and the deposed holder taking back bumps again
    assert lease.try_acquire_epoch(store, LEASE, "A", ttl=10.0, now=140.0) == 3


def test_fenced_write_rejected_after_deposition():
    """The VERDICT scenario: depose a leader, then its in-flight write
    (carrying the old epoch) must be REJECTED by the store. Times anchor at
    the wall clock because the fence's expiry check uses time.time()."""
    import time

    t0 = time.time()
    store = Store()
    assert lease.try_acquire_epoch(store, LEASE, "A", ttl=10.0, now=t0) == 1

    # while leading, fenced writes land
    store.create(_task("t1"), fence=_fence("A", 1))

    # B adopts after expiry -> epoch 2; A's stale-epoch write is rejected
    assert lease.try_acquire_epoch(store, LEASE, "B", ttl=10.0, now=t0 + 20) == 2
    with pytest.raises(Conflict, match="fencing"):
        store.create(_task("t2"), fence=_fence("A", 1))
    assert store.try_get("Task", "t2") is None, "fenced-out write must not land"

    # ...and updates/deletes are equally fenced
    t1 = store.get("Task", "t1")
    with pytest.raises(Conflict, match="fencing"):
        store.update_status(t1, fence=_fence("A", 1))
    with pytest.raises(Conflict, match="fencing"):
        store.delete("Task", "t1", fence=_fence("A", 1))

    # the new holder's token works
    store.create(_task("t2"), fence=_fence("B", 2))


def test_fence_rejects_missing_and_expired_lease():
    store = Store()
    with pytest.raises(Conflict, match="fencing"):
        store.create(_task("t1"), fence=_fence("A", 1))
    # an expired lease (nobody adopted yet) is equally not a license to
    # write. Expiry runs on the OWNER's clock (store._lease_touched), so
    # backdate that — the holder-written renew_time is deliberately not
    # what's checked (cross-host clock skew).
    import time

    lease.try_acquire_epoch(store, LEASE, "A", ttl=0.5)
    store._lease_touched[("Lease", "default", LEASE)] = time.time() - 1
    with pytest.raises(Conflict, match="fencing"):
        store.create(_task("t1"), fence=_fence("A", 1))


def test_fenced_store_view():
    """FencedStore injects the provider's token per call and fails fast
    when not leading."""
    store = Store()
    token: list[dict | None] = [None]
    fenced = FencedStore(store, lambda: token[0])

    with pytest.raises(Conflict, match="not the leader"):
        fenced.create(_task("t1"))

    assert lease.try_acquire_epoch(store, LEASE, "A", ttl=10.0, now=None) == 1
    token[0] = _fence("A", 1)
    fenced.create(_task("t1"))
    # reads pass through unfenced
    assert fenced.get("Task", "t1").metadata.name == "t1"

    # mutate_status does not retry a fencing Conflict (deposition is final)
    token[0] = _fence("A", 99)
    with pytest.raises(Conflict, match="fencing"):
        fenced.mutate_status(
            "Task", "t1", "default", lambda o: setattr(o.status, "phase", "Failed")
        )


def test_fence_travels_over_served_store(tmp_path):
    """Multi-replica reality: the elected leader may be a RemoteStore
    client, so the token must ride the RPC and be checked at the owner."""
    store = Store()
    server = StoreServer(store, f"unix://{tmp_path}/fence.sock").start()
    remote = RemoteStore(server.address, timeout=10.0)
    try:
        assert lease.try_acquire_epoch(remote, LEASE, "A", ttl=10.0) == 1
        remote.create(_task("t1"), fence=_fence("A", 1))
        # depose A directly at the owner
        lea = store.get("Lease", LEASE)
        lea.spec.holder_identity = "B"
        lea.spec.epoch = 2
        store.update(lea)
        with pytest.raises(Conflict, match="fencing"):
            remote.create(_task("t2"), fence=_fence("A", 1))
        remote.close()
    finally:
        server.stop()


async def test_leader_elector_mints_and_drops_tokens():
    store = Store()
    elector = LeaderElector(store, "A", ttl=10.0, renew_interval=0.05)
    elector.start()
    try:
        for _ in range(100):
            if elector.is_leader:
                break
            await asyncio.sleep(0.02)
        fence = elector.fence()
        assert fence is not None and fence["epoch"] == 1 and fence["holder"] == "A"
        store.create(_task("t1"), fence=fence)

        # forcibly hand the lease to B (epoch bump) — the OLD token dies
        # even while the elector still believes it leads
        lea = store.get("Lease", LEASE)
        lea.spec.holder_identity = "B"
        lea.spec.epoch = 2
        store.update(lea)
        with pytest.raises(Conflict, match="fencing"):
            store.create(_task("t2"), fence=fence)
    finally:
        await elector.stop()
    assert elector.fence() is None
