"""Workqueue dedup/backoff + controller manager watch->reconcile wiring."""

import asyncio

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LocalObjectRef,
    Task,
    TaskSpec,
    ToolCall,
    ToolCallSpec,
)
from agentcontrolplane_tpu.kernel import Manager, Result, Store, WorkQueue


async def test_queue_dedup_and_order():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2
    assert await q.get() == "a"
    # re-add while active -> goes dirty, re-queued on done()
    q.add("a")
    assert await q.get() == "b"
    q.done("b")
    q.done("a")
    assert await q.get() == "a"


async def test_queue_add_after_fires():
    q = WorkQueue()
    q.add_after("x", 0.05)
    t0 = asyncio.get_event_loop().time()
    assert await q.get() == "x"
    assert asyncio.get_event_loop().time() - t0 >= 0.04


async def test_queue_backoff_grows():
    q = WorkQueue()
    q.add_rate_limited("k")
    await q.get()
    q.done("k")
    q.add_rate_limited("k")
    t0 = asyncio.get_event_loop().time()
    assert await q.get() == "k"
    assert asyncio.get_event_loop().time() - t0 >= 0.008  # 5ms * 2^1
    q.forget("k")


class RecordingReconciler:
    def __init__(self, store):
        self.store = store
        self.seen = []

    async def reconcile(self, key):
        self.seen.append(key)
        return Result.done()


async def test_manager_watch_feeds_reconciler():
    store = Store()
    rec = RecordingReconciler(store)
    mgr = Manager(store)
    mgr.add_controller("task", "Task", rec, owns=["ToolCall"])
    await mgr.start()
    try:
        task = store.create(
            Task(
                metadata=ObjectMeta(name="t1"),
                spec=TaskSpec(agent_ref=LocalObjectRef(name="a"), user_message="m"),
            )
        )
        await mgr.run_until(lambda: ("Task", "default", "t1") in rec.seen, timeout=5)

        # owned ToolCall event maps to the owning Task's key (Owns() semantics)
        rec.seen.clear()
        store.create(
            ToolCall(
                metadata=ObjectMeta(name="t1-tc-01", owner_references=[task.owner_ref()]),
                spec=ToolCallSpec(
                    tool_call_id="1",
                    task_ref=LocalObjectRef(name="t1"),
                    tool_ref=LocalObjectRef(name="s__t"),
                    tool_type="MCP",
                ),
            )
        )
        await mgr.run_until(lambda: ("Task", "default", "t1") in rec.seen, timeout=5)
    finally:
        await mgr.stop()


async def test_leader_election_single_leader():
    store = Store()
    m1 = Manager(store, identity="pod-a", leader_election=True)
    m2 = Manager(store, identity="pod-b", leader_election=True)
    await m1.start()
    await asyncio.sleep(0.05)
    await m2.start()
    try:
        await m1.run_until(lambda: m1.elector.is_leader, timeout=5)
        await asyncio.sleep(0.1)
        assert not m2.elector.is_leader
    finally:
        await m1.stop()
        await m2.stop()
