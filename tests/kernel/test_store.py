"""Store semantics: CRUD, optimistic concurrency, labels, watch, GC, durability.

Mirrors what the reference gets from envtest (a real apiserver+etcd pair,
SURVEY.md §4): these are the invariants every controller test builds on.
"""

import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    Secret,
    SecretSpec,
    Task,
    TaskSpec,
    LocalObjectRef,
    ToolCall,
    ToolCallSpec,
)
from agentcontrolplane_tpu.kernel import (
    AlreadyExists,
    Conflict,
    NotFound,
    SqliteBackend,
    Store,
)


def mktask(name, labels=None, msg="hi"):
    return Task(
        metadata=ObjectMeta(name=name, labels=labels or {}),
        spec=TaskSpec(agent_ref=LocalObjectRef(name="a"), user_message=msg),
    )


def test_create_get_roundtrip(store):
    created = store.create(mktask("t1"))
    assert created.metadata.resource_version == 1
    assert created.metadata.generation == 1
    got = store.get("Task", "t1")
    assert got.spec.user_message == "hi"
    with pytest.raises(AlreadyExists):
        store.create(mktask("t1"))
    with pytest.raises(NotFound):
        store.get("Task", "missing")


def test_update_conflict_on_stale_rv(store):
    t = store.create(mktask("t1"))
    fresh = store.get("Task", "t1")
    fresh.spec.user_message = "updated"
    store.update(fresh)
    # stale copy now conflicts
    t.spec.user_message = "stale write"
    with pytest.raises(Conflict):
        store.update(t)


def test_spec_update_bumps_generation_status_update_does_not(store):
    t = store.create(mktask("t1"))
    t.spec.user_message = "v2"
    t = store.update(t)
    assert t.metadata.generation == 2
    t.status.phase = "Initializing"
    t = store.update_status(t)
    assert t.metadata.generation == 2
    assert t.metadata.resource_version == 3


def test_status_subresource_isolation(store):
    """update() must not clobber status; update_status() must not clobber spec."""
    t = store.create(mktask("t1"))
    t.status.phase = "Initializing"
    t = store.update_status(t)

    # spec-only update carrying a stale empty status
    fresh = store.get("Task", "t1")
    fresh.status.phase = ""  # simulates stale in-memory status
    fresh.spec.user_message = "v2"
    store.update(fresh)
    assert store.get("Task", "t1").status.phase == "Initializing"

    # status update carrying a stale spec
    fresh = store.get("Task", "t1")
    fresh.spec.user_message = "SHOULD NOT LAND"
    fresh.status.phase = "ReadyForLLM"
    store.update_status(fresh)
    got = store.get("Task", "t1")
    assert got.spec.user_message == "v2"
    assert got.status.phase == "ReadyForLLM"


def test_list_label_selector(store):
    store.create(mktask("t1", labels={"acp.tpu/task": "x", "req": "1"}))
    store.create(mktask("t2", labels={"acp.tpu/task": "x", "req": "2"}))
    store.create(mktask("t3", labels={"acp.tpu/task": "y"}))
    assert len(store.list("Task")) == 3
    assert {t.name for t in store.list("Task", label_selector={"acp.tpu/task": "x"})} == {"t1", "t2"}
    assert [t.name for t in store.list("Task", label_selector={"acp.tpu/task": "x", "req": "2"})] == ["t2"]


def test_owner_reference_gc_cascades(store):
    task = store.create(mktask("parent"))
    tc = ToolCall(
        metadata=ObjectMeta(name="parent-tc-01", owner_references=[task.owner_ref()]),
        spec=ToolCallSpec(
            tool_call_id="x",
            task_ref=LocalObjectRef(name="parent"),
            tool_ref=LocalObjectRef(name="srv__tool"),
            tool_type="MCP",
        ),
    )
    store.create(tc)
    # grandchild owned by the toolcall (delegation chain)
    child = mktask("delegate-child")
    child.metadata.owner_references = [tc.owner_ref()]
    store.create(child)

    store.delete("Task", "parent")
    assert store.try_get("ToolCall", "parent-tc-01") is None
    assert store.try_get("Task", "delegate-child") is None


def test_mutate_status_retries_conflicts(store):
    store.create(mktask("t1"))

    calls = {"n": 0}

    def bump(obj):
        calls["n"] += 1
        if calls["n"] == 1:
            # interleaved writer causes one conflict
            fresh = store.get("Task", "t1")
            fresh.status.status_detail = "interleaved"
            store.update_status(fresh)
        obj.status.phase = "Initializing"

    out = store.mutate_status("Task", "t1", "default", bump)
    assert out.status.phase == "Initializing"
    assert calls["n"] == 2


async def test_watch_stream(store):
    watch = store.watch("Task")
    store.create(mktask("t1"))
    ev = await watch.next(timeout=1)
    assert ev is not None and ev.type == "ADDED" and ev.object.name == "t1"

    t = store.get("Task", "t1")
    t.status.phase = "Initializing"
    store.update_status(t)
    ev = await watch.next(timeout=1)
    assert ev.type == "MODIFIED" and ev.object.status.phase == "Initializing"

    store.delete("Task", "t1")
    ev = await watch.next(timeout=1)
    assert ev.type == "DELETED"
    watch.stop()


def test_sqlite_durability_restart_resumes(tmp_path):
    """Operator restart = resume: all state survives in the backend
    (the reference's defining checkpoint/resume property)."""
    db = str(tmp_path / "state.db")
    s1 = Store(SqliteBackend(db))
    t = s1.create(mktask("t1"))
    t.status.phase = "ReadyForLLM"
    t.status.context_window = []
    s1.update_status(t)
    s1.create(Secret(metadata=ObjectMeta(name="k"), spec=SecretSpec(data={"a": "b"})))
    s1.close()

    s2 = Store(SqliteBackend(db))
    got = s2.get("Task", "t1")
    assert got.status.phase == "ReadyForLLM"
    assert got.metadata.resource_version == t.metadata.resource_version + 1
    assert s2.get("Secret", "k").spec.data == {"a": "b"}
    # new writes continue from the persisted rv watermark
    s2.create(mktask("t2"))
    assert s2.get("Task", "t2").metadata.resource_version > got.metadata.resource_version
    s2.close()


@pytest.mark.filterwarnings("ignore::UserWarning")  # intentional bad value
def test_update_rejects_invalid_object_state(store):
    """A wrong-typed assignment (pydantic doesn't validate on assignment)
    must be rejected at admission, never persisted."""
    from agentcontrolplane_tpu.kernel.errors import Invalid

    store.create(mktask("t1"))
    t = store.get("Task", "t1")
    t.spec.user_message = 123  # type: ignore[assignment]
    with pytest.raises(Invalid, match="invalid object state"):
        store.update(t)
    # the stored object is intact and readable
    assert store.get("Task", "t1").spec.user_message == "hi"
    # and the store still accepts valid writes afterwards
    fresh = store.get("Task", "t1")
    fresh.spec.user_message = "ok"
    assert store.update(fresh).spec.user_message == "ok"


def test_rv_counter_survives_restart_after_deletes(tmp_path):
    """The monotonic resource_version counter is persisted (meta table), so
    deleting the highest-rv objects before a restart cannot cause previously
    issued rvs to be re-issued afterwards (which would defeat optimistic
    concurrency for clients holding pre-restart objects)."""
    path = str(tmp_path / "state.db")
    s1 = Store(SqliteBackend(path))
    keep = s1.create(mktask("keep"))
    hot = s1.create(mktask("hot"))
    hot = s1.update_status(hot)  # bump rv further
    high_rv = hot.metadata.resource_version
    assert high_rv > keep.metadata.resource_version
    s1.delete("Task", "hot")
    s1.close()

    s2 = Store(SqliteBackend(path))
    fresh = s2.create(mktask("fresh"))
    assert fresh.metadata.resource_version > high_rv
    s2.close()


def test_precondition_delete(store):
    obj = store.create(mktask("l1"))
    old_rv = obj.metadata.resource_version
    obj2 = store.get("Task", "l1")
    store.update_status(obj2)  # rv moves on
    with pytest.raises(Conflict):
        store.delete("Task", "l1", resource_version=old_rv)
    assert store.try_get("Task", "l1") is not None
    cur = store.get("Task", "l1")
    store.delete("Task", "l1", resource_version=cur.metadata.resource_version)
    assert store.try_get("Task", "l1") is None
