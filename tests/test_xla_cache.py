"""xla_cache: the persistent compile cache must refuse to arm in
multi-host processes (divergent collective decompositions across ranks —
see tests/parallel/mp_serve_worker.py) and honor the opt-out env."""

from __future__ import annotations

import jax

from agentcontrolplane_tpu import xla_cache


def test_cache_disabled_for_multihost(monkeypatch):
    monkeypatch.setattr(xla_cache, "_enabled", False)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert xla_cache.enable_persistent_compilation_cache() is False


def test_cache_env_opt_out(monkeypatch):
    monkeypatch.setattr(xla_cache, "_enabled", False)
    monkeypatch.setenv("ACP_XLA_CACHE", "0")
    assert xla_cache.enable_persistent_compilation_cache() is False


def test_cache_enables_single_process(monkeypatch, tmp_path):
    monkeypatch.setattr(xla_cache, "_enabled", False)
    monkeypatch.setenv("ACP_XLA_CACHE_DIR", str(tmp_path / "cache"))
    # record instead of mutating REAL global jax config (the tmp dir is
    # deleted after this test; later compiles must not point at it)
    updates: dict = {}
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: updates.__setitem__(k, v)
    )
    assert xla_cache.enable_persistent_compilation_cache() is True
    assert (tmp_path / "cache").is_dir()
    assert updates["jax_compilation_cache_dir"] == str(tmp_path / "cache")
