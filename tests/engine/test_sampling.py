"""Bisection-threshold sampler vs an exact numpy nucleus/top-k oracle.

The sampler replaces the two full-vocab sorts with threshold binary
searches (ops/sampling.py); these tests pin the masking semantics: a
sampled token must always lie inside the exact allowed set, and greedy
(temperature 0) must be untouched by the masks.
"""

import numpy as np

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.ops.sampling import sample


def _exact_allowed(logits: np.ndarray, top_k: int, top_p: float) -> set:
    """Oracle: indices surviving top-k (keep k largest, ties kept) then
    top-p (keep tokens whose strictly-greater-prob mass is < top_p)."""
    V = logits.shape[0]
    x = logits.astype(np.float64).copy()
    if top_k > 0 and top_k < V:
        kth = np.sort(x)[::-1][top_k - 1]
        x[x < kth] = -np.inf
    e = np.exp(x - np.max(x[np.isfinite(x)]))
    e[~np.isfinite(x)] = 0.0
    p = e / e.sum()
    allowed = set()
    # mass of strictly-greater-probability tokens, per token
    for i in range(V):
        if p[i] <= 0:
            continue
        mass_above = p[p > p[i]].sum()
        if mass_above < top_p:
            allowed.add(i)
    return allowed


def test_sampled_tokens_stay_inside_exact_nucleus():
    rng = np.random.default_rng(0)
    V, S = 64, 4
    logits_np = rng.normal(scale=3.0, size=(S, V)).astype(np.float32)
    logits = jnp.asarray(logits_np)
    temps = jnp.asarray([0.7, 1.3, 0.9, 2.0])
    top_ks = jnp.asarray([0, 5, 3, 8], dtype=jnp.int32)
    top_ps = jnp.asarray([0.8, 1.0, 0.5, 0.9])
    allowed = [
        _exact_allowed(logits_np[s], int(top_ks[s]), float(top_ps[s]))
        for s in range(S)
    ]
    for trial in range(64):
        toks = np.asarray(
            sample(logits, jax.random.key(trial), temps, top_ks, top_ps)
        )
        for s in range(S):
            assert int(toks[s]) in allowed[s], (
                f"slot {s} trial {trial}: token {toks[s]} outside exact "
                f"top_k={int(top_ks[s])}/top_p={float(top_ps[s])} set"
            )


def test_greedy_unaffected_by_masks():
    rng = np.random.default_rng(1)
    logits_np = rng.normal(size=(3, 128)).astype(np.float32)
    toks = np.asarray(
        sample(
            jnp.asarray(logits_np),
            jax.random.key(0),
            jnp.zeros(3),  # temperature 0 -> greedy
            jnp.asarray([4, 0, 1], dtype=jnp.int32),
            jnp.asarray([0.3, 0.01, 1.0]),
        )
    )
    np.testing.assert_array_equal(toks, logits_np.argmax(-1))


def test_top_k_one_is_greedy_even_at_high_temperature():
    rng = np.random.default_rng(2)
    logits_np = rng.normal(size=(2, 256)).astype(np.float32)
    for trial in range(16):
        toks = np.asarray(
            sample(
                jnp.asarray(logits_np),
                jax.random.key(trial),
                jnp.full((2,), 5.0),
                jnp.ones(2, dtype=jnp.int32),  # top_k=1
                jnp.ones(2),
            )
        )
        np.testing.assert_array_equal(toks, logits_np.argmax(-1))
