"""N-gram prompt-lookup speculative decoding with batched in-engine
verification.

The load-bearing guarantee: greedy outputs with speculation ON are
byte-identical to the non-speculative engine across the whole stress
matrix — preemption, forced full rejection, prefix-cache hits — because
the accept op emits the VERIFIED argmax at every position; drafts only
decide how many positions commit per dispatch. On repetitive agent-style
traffic (tool echo) each verify dispatch must land well over one token.

Engines are expensive to construct on CPU (each compiles its program set),
so the identity tests share four module-scoped engines (spec on/off x
slot/paged, one geometry); only the stress matrix and the ctx-edge pin
build their own.
"""

import dataclasses
import time

import numpy as np
import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.spec import (
    REPROBE_DISPATCHES,
    SpecState,
    ngram_propose,
)
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)

# repeated tool-call JSON — the self-similar agent traffic shape the
# drafter exploits (and which drives this random-weights model into a
# repetition attractor, so the drafter predicts its greedy output too)
TOOL_ECHO = '{"tool": "search", "args": {"q": "x"}} {"tool": "search", "args": {"q": "x"}}'


def make_engine(kv_layout="slot", spec_len=8, max_ctx=256, **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    # ACP_INVARIANTS posture for the whole stress suite: every run
    # double-checks the engine's bookkeeping after each dispatch cycle
    kw.setdefault("check_invariants", True)
    kw.setdefault("prefill_buckets", (64, 256))
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=max_ctx,
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        spec_len=spec_len,
        **kw,
    )
    eng.start()
    return eng


@pytest.fixture(scope="module")
def engines():
    """Shared spec-on/spec-off engine pairs, both layouts, one geometry."""
    pool = {
        ("slot", 0): make_engine("slot", spec_len=0),
        ("slot", 6): make_engine("slot", spec_len=6),
        ("paged", 0): make_engine("paged", spec_len=0),
        ("paged", 6): make_engine("paged", spec_len=6),
    }
    yield pool
    for eng in pool.values():
        eng.stop()


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- drafter + controller units ----------------------------------------------


def test_ngram_propose_prefers_longest_then_most_recent():
    ctx = np.array([1, 2, 3, 9, 1, 2, 3, 7, 8, 1, 2, 3], dtype=np.int64)
    # tail 3-gram (1,2,3) occurs at 0 (-> 9) and 4 (-> 7,8); recency wins
    assert ngram_propose(ctx, 3, 4) == [7, 8, 1, 2]
    assert ngram_propose(ctx, 3, 1) == [7]
    # with ngram_max=1, the tail 1-gram (3) most recently continued with 7
    assert ngram_propose(ctx, 1, 2) == [7, 8]


def test_ngram_propose_falls_back_to_shorter_ngrams_and_handles_no_match():
    # tail (5, 6) never occurred before, but 6 did -> 1-gram fallback
    ctx = np.array([6, 4, 5, 6], dtype=np.int64)
    assert ngram_propose(ctx, 3, 3) == [4, 5, 6]
    assert ngram_propose(np.array([1, 2, 3, 4], dtype=np.int64), 3, 4) == []
    assert ngram_propose(np.array([7], dtype=np.int64), 3, 4) == []
    assert ngram_propose(np.array([7, 7, 7], dtype=np.int64), 3, 0) == []


def test_ngram_propose_periodic_overlap():
    # period-1 repetition: the matched window may overlap the tail's own,
    # and an older match with a FULL continuation beats the most recent
    # one clipped at the context edge
    ctx = np.array([9, 9, 9, 9], dtype=np.int64)
    assert ngram_propose(ctx, 3, 2) == [9, 9]
    # period-2 loop: full-length draft continues the cycle
    ctx = np.array([4, 5, 4, 5, 4, 5], dtype=np.int64)
    assert ngram_propose(ctx, 3, 4) == [4, 5, 4, 5]


def test_spec_state_decay_growth_and_reprobe():
    st = SpecState(limit=8)
    assert st.cap() == 8  # optimistic start
    st.observe(8, 0)  # full rejection halves
    assert st.cap() == 4
    st.observe(4, 0)
    st.observe(2, 0)
    st.observe(1, 0)
    assert st.cap() == 0  # decayed all the way to the non-speculative path
    # parked at 0: re-probes with a 1-token draft on the REPROBE-th dispatch
    seq = [st.cap() for _ in range(REPROBE_DISPATCHES - 1)]
    assert all(c == 0 for c in seq[:-1]) and seq[-1] == 1
    st.observe(1, 1)  # full acceptance doubles
    assert st.cap() == 2
    st.observe(2, 1)  # partial acceptance: additive step
    assert st.cap() == 3
    st.observe(3, 0)  # no-draft dispatches teach nothing
    st.observe(0, 0)
    assert st.cur == 1


# -- model layer: the verify pass is the exact model ------------------------


def test_verify_continue_matches_full_forward():
    """verify_continue's all-position logits must agree with the plain
    full-sequence forward at every continuation position — argmax equality
    is what the greedy byte-identity guarantee rides on."""
    import jax.numpy as jnp

    from agentcontrolplane_tpu.models.llama import (
        forward,
        init_kv_cache,
        init_params,
        prefill,
        verify_continue,
    )

    cfg = PRESETS["tiny"]
    params = init_params(cfg, jax.random.key(0))
    cache = init_kv_cache(cfg, 2, 64)
    prompt = jnp.array([5, 7, 11, 13, 17, 19], dtype=jnp.int32)
    cont = jnp.array([23, 29, 31, 37], dtype=jnp.int32)
    cache, _ = prefill(params, cache, prompt, jnp.int32(len(prompt)), jnp.int32(0), cfg)
    tokens = jnp.zeros((2, 6), dtype=jnp.int32).at[0, : len(cont)].set(cont)
    lengths = jnp.array([len(cont), 1], dtype=jnp.int32)
    starts = jnp.array([len(prompt), 0], dtype=jnp.int32)
    _, logits = verify_continue(params, cache, tokens, lengths, starts, cfg)
    full = forward(params, jnp.concatenate([prompt, cont])[None], cfg)[0]
    for i in range(len(cont)):
        ref = full[len(prompt) + i]
        got = logits[0, i]
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=2e-4, rtol=2e-4)
        assert int(jnp.argmax(got)) == int(jnp.argmax(ref))


# -- engine: greedy byte-identity --------------------------------------------


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_greedy_byte_identity_and_streams(engines, kv_layout):
    sp = SamplingParams(temperature=0.0, max_tokens=20)
    prompts = ["abcabcabcabcabc", TOOL_ECHO[:30], "hello world"]
    off, on = engines[(kv_layout, 0)], engines[(kv_layout, 6)]
    ref = {p: off.generate(p, sp).tokens for p in prompts}
    disp0 = on.spec_dispatches
    for p in prompts:
        stream: list[int] = []
        r = on.submit(p, sp, on_tokens=stream.extend).result(timeout=120)
        assert r.tokens == ref[p], f"spec-on diverged for {p!r} ({kv_layout})"
        assert stream == r.tokens, "streamed tokens must match exactly once"
    assert on.stats()["spec"]["enabled"]
    assert on.spec_dispatches > disp0, "speculation must actually have run"


def test_json_constrained_greedy_identity_with_spec(engines):
    """Grammar-constrained decoding composes: the verify path masks logits
    through the same automaton with the same budget-aware closure."""
    sp = SamplingParams(temperature=0.0, max_tokens=24, json_only=True)
    ref = engines[("slot", 0)].generate("make json", sp)
    r = engines[("slot", 6)].generate("make json", sp)
    assert r.tokens == ref.tokens


def test_max_tokens_budget_exact_with_multi_token_commits(engines):
    """Speculation lands several tokens per dispatch; the device budget
    decrement and the host max_tokens accounting must clip at EXACTLY the
    same token (an odd cap forces a mid-dispatch clip)."""
    sp = SamplingParams(temperature=0.0, max_tokens=17)
    ref = engines[("slot", 0)].generate(TOOL_ECHO, sp)
    r = engines[("slot", 6)].generate(TOOL_ECHO, sp)
    assert r.tokens == ref.tokens
    if r.finish_reason == "length":
        assert len(r.tokens) == sp.max_tokens


def test_spec_composes_with_prefix_cache_hits(engines):
    """Multi-turn agent shape: turn 2 extends turn 1's prompt, hits the
    prefix cache, AND speculates — output must equal the spec-off engine's."""
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    turn1 = "sys: you are a tool agent. " + "abc" * 16
    turn2 = turn1 + " user: again again again"
    outputs = {}
    for spec_len in (0, 6):
        eng = engines[("slot", spec_len)]
        eng.generate(turn1, sp)
        hits0 = eng._prefix_hits
        outputs[spec_len] = eng.generate(turn2, sp).tokens
        assert eng._prefix_hits > hits0, "turn 2 must hit the prefix cache"
    assert outputs[6] == outputs[0]


# -- the acceptance-rate criterion -------------------------------------------


def test_tool_echo_fixture_accepts_over_1_5_tokens_per_dispatch(engines):
    """On repetitive tool-echo traffic the engine must commit > 1.5 tokens
    per decode dispatch (the CPU-backend acceptance bar), and the decode-
    efficiency stats must say so."""
    eng = engines[("slot", 6)]
    before = counter("acp_engine_spec_accepted_total")
    tok0, step0, acc0, prop0 = (
        eng.tokens_generated, eng.decode_steps, eng.spec_accepted, eng.spec_proposed,
    )
    r = eng.generate(TOOL_ECHO, SamplingParams(temperature=0.0, max_tokens=120))
    assert len(r.tokens) > 60  # long enough to be a real measurement
    per_step = (eng.tokens_generated - tok0) / (eng.decode_steps - step0)
    assert per_step > 1.5, per_step
    accepted = eng.spec_accepted - acc0
    assert 0 < accepted <= eng.spec_proposed - prop0
    s = eng.stats()
    assert s["tokens_per_decode_step"] > 0
    assert 0.0 < s["spec"]["acceptance_rate"] <= 1.0
    assert counter("acp_engine_spec_accepted_total") == before + accepted


# -- fault injection: forced worst case --------------------------------------


def test_spec_mismatch_fault_forces_full_rejection_byte_identically(engines):
    sp = SamplingParams(temperature=0.0, max_tokens=24)
    eng = engines[("slot", 6)]
    baseline = eng.generate(TOOL_ECHO, sp)
    acc0, disp0 = eng.spec_accepted, eng.spec_dispatches
    FAULTS.arm("engine.spec_mismatch", times=1000)  # every verify pass
    r = eng.generate(TOOL_ECHO, sp)
    assert r.tokens == baseline.tokens  # worst case still byte-identical
    assert eng.spec_accepted == acc0, "forced mismatch must reject every draft"
    assert eng.spec_dispatches > disp0, "verification must still have run"
    FAULTS.disarm("engine.spec_mismatch")
    # and with the fault gone, acceptance returns
    r2 = eng.generate(TOOL_ECHO, sp)
    assert r2.tokens == baseline.tokens
    assert eng.spec_accepted > acc0


def test_adaptive_decay_under_permanent_mismatch_reaches_block_path(engines):
    """Under permanent forced mismatch the per-slot cap decays to 0 and the
    engine falls back to plain decode blocks (today's path): decode_steps
    grows by K per block again instead of 1 per verify dispatch."""
    eng = engines[("slot", 6)]
    FAULTS.arm("engine.spec_mismatch", times=10_000)
    acc0, disp0 = eng.spec_accepted, eng.spec_dispatches
    r = eng.generate(TOOL_ECHO, SamplingParams(temperature=0.0, max_tokens=80))
    assert len(r.tokens) > 0
    # cap decays 6 -> 3 -> 1 -> 0 after 3 full rejections; the long tail
    # must run as plain blocks, so verify dispatches stay a small fraction
    # of the work (bounded by the decay plus periodic re-probes)
    assert eng.spec_dispatches - disp0 < 20, eng.spec_dispatches - disp0
    assert eng.spec_accepted == acc0


# -- stress matrix: speculation x preemption x mismatch ----------------------


def _stress(n_requests: int, max_tokens: int):
    """Oversubscribed paged pool with speculation ON under forced spec
    mismatch + forced preemption: every greedy output must equal its
    speculation-OFF uncontended run, streamed exactly once."""
    sp = SamplingParams(temperature=0.0, max_tokens=max_tokens)
    prompts = [ch * 20 for ch in "abcdef"[:n_requests]]
    off = make_engine("paged", spec_len=0, max_ctx=64,
                      prefill_buckets=(32, 64), kv_pages=10)
    try:
        solo = {p: off.generate(p, sp).tokens for p in prompts}
    finally:
        off.stop()
    eng = make_engine("paged", spec_len=6, max_ctx=64,
                      prefill_buckets=(32, 64), kv_pages=10)
    try:
        FAULTS.arm("engine.spec_mismatch", times=3)
        FAULTS.arm("engine.force_preempt", after_steps=4)
        streams = {p: [] for p in prompts}
        with eng.hold_admission():
            futs = [eng.submit(p, sp, on_tokens=streams[p].extend) for p in prompts]
        results = dict(zip(prompts, (f.result(timeout=240) for f in futs)))
        for p, r in results.items():
            assert r.tokens == solo[p], f"stress output diverged for {p!r}"
            assert streams[p] == r.tokens, "streamed tokens must arrive exactly once"
            assert r.finish_reason in ("stop", "length")
        assert any(r.preempt_count >= 1 for r in results.values())
        # pages fully recycled once the burst drains
        deadline = time.monotonic() + 5
        while eng._allocator.free_count != eng.num_pages - 1:
            assert time.monotonic() < deadline, "leaked KV pages"
            time.sleep(0.05)
    finally:
        eng.stop()


def test_stress_oversubscribed_spec_preempt_mismatch():
    _stress(n_requests=4, max_tokens=10)


@pytest.mark.slow
def test_stress_oversubscribed_spec_preempt_mismatch_heavy():
    _stress(n_requests=6, max_tokens=16)


def test_reclaim_floor_honors_in_flight_spec_dispatch_need():
    """A speculative verify dispatch writes 1 + draft KV rows — more than
    the decode block. Mid-pass, a later slot's allocation must not claw
    back pages an earlier slot was just granted for its draft tail: the
    dispatch would write that KV to the trash page while the host advances
    seq_len over it, corrupting every later attention pass. Bare-object
    harness; no compiled engine needed."""
    from agentcontrolplane_tpu.engine.engine import Engine, _Slot
    from agentcontrolplane_tpu.ops.paged import TRASH_PAGE, PageAllocator

    eng = Engine.__new__(Engine)
    eng.page_size = 8
    eng.decode_block_size = 4
    eng.max_pages_per_seq = 8
    eng._allocator = PageAllocator(4)  # pages 1..3 usable (0 = trash)
    eng._seq_lens = np.zeros(4, dtype=np.int32)
    eng._block_tables = np.full((4, 8), TRASH_PAGE, dtype=np.int32)
    eng._tables_dirty = False
    eng._slots = {0: _Slot(request=None), 1: _Slot(request=None)}
    # slot 0: seq_len 2, granted 2 pages covering its 1+6-row verify
    # dispatch (ceil((2+7)/8) = 2); slot 1 holds the third page
    eng._seq_lens[0] = 2
    eng._slot_pages = {0: eng._allocator.alloc(2), 1: eng._allocator.alloc(1)}
    eng._block_tables[0, :2] = eng._slot_pages[0]
    eng._block_tables[1, :1] = eng._slot_pages[1]

    # pool exhausted; slot 1 asks for one more page with the dispatch
    # needs threaded: slot 0's floor is ceil((2 + max(4, 7)) / 8) = 2
    # pages — nothing reclaimable, the allocation must fail (escalating
    # to preemption) rather than strip slot 0's granted coverage
    assert eng._alloc_reclaiming_lookahead(1, 1, {0: 7, 1: 4}) is None
    assert len(eng._slot_pages[0]) == 2
    assert eng._block_tables[0, 1] != TRASH_PAGE

    # the plain block path (no dispatch needs) reclaims the page beyond
    # slot 0's strict K-token window (ceil((2 + 4) / 8) = 1 page)
    got = eng._alloc_reclaiming_lookahead(1, 1, None)
    assert got is not None and len(got) == 1
    assert len(eng._slot_pages[0]) == 1
    assert eng._block_tables[0, 1] == TRASH_PAGE


# -- ctx-edge accounting with multi-token commits ----------------------------


@pytest.mark.slow
def test_ctx_edge_off_by_one_pinned_at_max_ctx_minus_1():
    """Regression pin for the max_ctx - 1 edge: a generation that runs to
    the context edge finishes 'length' with prompt + generated == max_ctx
    (the last sampled token lands the sequence at seq_len == max_ctx - 1;
    KV is never written at row max_ctx - 1), identically with speculation
    on and off."""
    sp = SamplingParams(temperature=0.0, max_tokens=500)
    results = {}
    for spec_len in (0, 6):
        eng = make_engine(spec_len=spec_len, max_ctx=64, prefill_buckets=(32, 64))
        try:
            results[spec_len] = eng.generate("abcabcabcabcabc", sp)
        finally:
            eng.stop()
    ref, spec = results[0], results[6]
    assert spec.tokens == ref.tokens
    assert ref.finish_reason == spec.finish_reason
    if ref.finish_reason == "length" and len(ref.tokens) < sp.max_tokens:
        # the edge case this test exists for: generation clipped by ctx
        assert ref.prompt_tokens + len(ref.tokens) == 64


def test_ctx_edge_off_by_one_shared_geometry(engines):
    """Tier-1 ctx-edge pin on the shared engines: a prompt near the 256
    context edge must clip at exactly prompt + generated == max_ctx with
    identical tokens spec-on and spec-off."""
    sp = SamplingParams(temperature=0.0, max_tokens=500)
    prompt = TOOL_ECHO * 3  # ~230 tokens: a dozen tokens of decode room
    ref = engines[("slot", 0)].generate(prompt, sp)
    r = engines[("slot", 6)].generate(prompt, sp)
    assert r.tokens == ref.tokens
    assert r.finish_reason == ref.finish_reason
    if ref.finish_reason == "length" and len(ref.tokens) < sp.max_tokens:
        assert ref.prompt_tokens + len(ref.tokens) == 256
