"""Quantized KV serving (ISSUE 14): the int8 cache across the engine's
whole mechanism matrix, and the byte-identity-relaxed accuracy gate.

What is (and is not) exact under ``quantize_kv``:

- **Run-to-run determinism** — always bit-exact, every configuration.
- **Host-swap round trips** — bit-exact vs the unpreempted same-knob run:
  the int8 bytes + scale rows travel to host RAM and back verbatim (no
  requantization), so preempt-resume through the host tier cannot move a
  token.
- **Megastep fused vs split** — bit-exact: the same phase bodies run in
  the same order on the same quantized bytes; only the dispatch boundary
  moves.
- **vs the bf16 path** — NOT bit-exact (the one legitimate break): gated
  by the pinned accuracy fixture instead (top-1 greedy agreement +
  logit-MAE bounds, thresholds pinned here).
- **Both knobs off** — the cache carries no scale storage at all and the
  plain path stays bit-for-bit (the existing byte-identity matrix is
  untouched; the purity pin below makes the no-scale-storage contract
  explicit).
"""

import dataclasses
import time

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from agentcontrolplane_tpu.engine.accuracy import (
    accuracy_report,
    check_accuracy_gate,
    pinned_fixture,
)
from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.invariants import verify_engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS, init_params
from agentcontrolplane_tpu.ops.quant import SCALE_FLOOR, kv_dequantize, kv_quantize
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
TINY = PRESETS["tiny"]
CFG = dataclasses.replace(TINY, vocab_size=512, max_seq_len=256, n_kv_heads=2)

# The pinned gate thresholds (tiny preset, default fixture). Measured at
# pinning time: weights-only 0.984/0.0138, kv-only 0.990/0.0046, both
# 0.990/0.0146 — the margins absorb compiler jitter, not behavior drift.
GATE_MIN_TOP1 = 0.92
GATE_MAX_MAE = 0.05


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    kw.setdefault("quantize_kv", True)
    kw.setdefault("max_ctx", 64)
    kw.setdefault("prefill_buckets", (32, 64))
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def _settle(eng: Engine) -> None:
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (eng._has_work() or len(eng._waiting)):
        time.sleep(0.01)
    time.sleep(0.1)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- numerics ----------------------------------------------------------------


def test_kv_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 7, 4, 16)), dtype=jnp.float32)
    q, scale = kv_quantize(x)
    assert q.dtype == jnp.int8 and scale.shape == (5, 7, 4)
    err = np.abs(np.asarray(kv_dequantize(q, scale, jnp.float32)) - np.asarray(x))
    # symmetric int8 over head_dim: max error is scale/2 per row
    assert err.max() <= float(np.asarray(scale).max()) * 0.51


def test_kv_quantize_all_zero_rows_take_scale_floor():
    """The guard satellite, KV side: all-zero rows (never-written cache,
    padding lanes) must produce the floor scale — finite, and an exact
    zero round trip — never a 0/0 NaN that poisons later reads."""
    x = jnp.zeros((2, 3, 8), dtype=jnp.float32)
    q, scale = kv_quantize(x)
    assert np.all(np.asarray(scale) == SCALE_FLOOR)
    out = np.asarray(kv_dequantize(q, scale, jnp.float32))
    assert np.all(np.isfinite(out)) and np.all(out == 0.0)


# -- the accuracy gate -------------------------------------------------------


@pytest.mark.parametrize(
    "qw,qkv", [(True, False), (False, True), (True, True)]
)
def test_accuracy_gate_passes_pinned_thresholds(qw, qkv):
    """The byte-identity-relaxed contract: every quantized configuration
    clears the pinned top-1 agreement + logit-MAE gate over the pinned
    fixture, scored through the real serving numerics."""
    params = init_params(TINY, jax.random.key(0))
    rep = accuracy_report(TINY, params, quantize_weights=qw, quantize_kv=qkv)
    assert check_accuracy_gate(rep, GATE_MIN_TOP1, GATE_MAX_MAE) == [], rep
    # and the un-quantized baseline is self-identical (sanity: the fixture
    # harness itself introduces zero noise)
    base = accuracy_report(TINY, params)
    assert base["top1_agreement"] == 1.0 and base["logit_mae"] == 0.0


def test_pinned_fixture_is_pinned():
    """Same (vocab, shape, seed) -> same rows, forever: the gate's fixture
    is a contract, not a re-roll."""
    a = pinned_fixture(TINY.vocab_size)
    b = pinned_fixture(TINY.vocab_size)
    assert a.shape == (4, 48) and np.array_equal(a, b)
    assert a.min() >= 1 and a.max() < TINY.vocab_size


# -- the serving matrix ------------------------------------------------------


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
@pytest.mark.parametrize("spec_len", [0, 4])
@pytest.mark.parametrize("prefill_chunk", [0, 16])
def test_quantized_matrix_serves_deterministically(kv_layout, spec_len, prefill_chunk):
    """Both layouts x spec on/off x chunked on/off, armed checker on:
    quantized serving is run-to-run deterministic and audits clean.
    (Cross-config byte-identity is NOT asserted — chunk boundaries and
    draft windows change which reads see exact vs quantized rows, the
    relaxation the accuracy gate owns.)"""
    eng = make_engine(kv_layout, spec_len=spec_len, prefill_chunk=prefill_chunk)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=10)
        prompt = "abcabcabc " * 4  # attractor so spec cells really draft
        r1 = eng.generate(prompt, sp)
        r2 = eng.generate(prompt, sp)
        assert r1.finish_reason in ("stop", "length")
        assert r1.tokens == r2.tokens
        if spec_len:
            assert eng.spec_dispatches > 0, "spec cell never speculated"
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_quantized_swap_roundtrip_bit_exact(kv_layout):
    """Preempt -> host swap -> resume under quantize_kv is bit-exact vs
    the unpreempted run: the int8 bytes + scale rows restore verbatim
    (no requantization round trip), spec on, armed checker auditing."""
    eng = make_engine(kv_layout, host_kv_bytes=1 << 22, spec_len=4)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=14)
        base = eng.generate("hello world " * 4, sp).tokens
        FAULTS.arm("engine.force_preempt", after_steps=2)
        r = eng.generate("hello world " * 4, sp)
        assert r.preempt_count >= 1
        assert r.tokens == base, "quantized swap round-trip moved a token"
        assert eng.kv_swap_outs >= 1 and eng.kv_swap_ins >= 1
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_quantized_megastep_fused_equals_split():
    """Fused vs split dispatches run the identical schedule on the same
    quantized bytes — bit-for-bit equal, chunked + spec active."""
    outs = {}
    for mega in (False, True):
        eng = make_engine("paged", megastep=mega, prefill_chunk=16, spec_len=4)
        try:
            sp = SamplingParams(temperature=0.0, max_tokens=12)
            with eng.hold_admission():
                futs = [
                    eng.submit("the quick brown fox jumps over " * 3, sp),
                    eng.submit("pack my box with five dozen jugs " * 2, sp),
                    eng.submit("abcabcabc " * 4, sp),
                ]
            outs[mega] = [f.result(timeout=300).tokens for f in futs]
            if mega:
                assert eng.megastep_dispatches > 0
            _settle(eng)
            assert verify_engine(eng) == []
        finally:
            eng.stop()
    assert outs[True] == outs[False]


def test_quantized_park_adopt_roundtrip():
    """Two-turn park/adopt conversation with quantize_kv: the parked
    quantized prompt rows are adopted suffix-only; deterministic across
    repeats and audited clean."""
    turn1 = "persona prompt " * 4
    turn2 = turn1 + " and then a follow up"
    sp = SamplingParams(temperature=0.0, max_tokens=10)

    def run():
        eng = make_engine("paged", max_ctx=128, prefill_buckets=(32, 64, 128))
        try:
            r1 = eng.submit(turn1, sp, park=True).result(timeout=180)
            r2 = eng.submit(turn2, sp).result(timeout=180)
            adoptions = eng.park_adoptions
            _settle(eng)
            assert verify_engine(eng) == []
            return r1.tokens, r2.tokens, adoptions
        finally:
            eng.stop()

    t1a, t2a, adopt_a = run()
    t1b, t2b, _ = run()
    assert adopt_a >= 1, "turn 2 never adopted the parked slot"
    assert (t1a, t2a) == (t1b, t2b)


def test_quantized_dedup_burst_shares_and_matches_solo():
    """A same-persona burst over quantized pages refcount-shares the int8
    prompt pages; outputs equal the solo runs exactly (same quantized
    bytes, shared or private)."""
    eng = make_engine("paged", prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        persona = "agent persona prompt! " * 2
        solo = {i: eng.generate(persona + str(i), sp).tokens for i in range(4)}
        shared_peak = [0]

        def on_tokens(_t):
            shared_peak[0] = max(
                shared_peak[0],
                eng.stats()["memory"]["prefix_dedup"]["shared_pages"],
            )

        with eng.hold_admission():
            futs = [
                eng.submit(persona + str(i), sp, on_tokens=on_tokens)
                for i in range(4)
            ]
        res = {i: f.result(timeout=180).tokens for i, f in enumerate(futs)}
        assert res == solo
        assert shared_peak[0] > 0, "burst never shared a quantized page"
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


# -- off-knob purity ---------------------------------------------------------


def test_off_knobs_carry_no_scale_storage():
    """Both knobs off: the cache is exactly the plain {k, v} bf16/f32
    layout (no scale twins, no int8) — the structural half of 'the
    existing byte-identity matrix passes untouched'."""
    for layout in ("slot", "paged"):
        eng = make_engine(layout, quantize_kv=False)
        try:
            assert sorted(eng.cache) == ["k", "v"]
            assert eng.cache["k"].dtype == CFG.dtype
            assert not eng.quantize_kv and eng.quantize is None
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            r1 = eng.generate("plain path purity", sp)
            r2 = eng.generate("plain path purity", sp)
            assert r1.tokens == r2.tokens
            _settle(eng)
            assert verify_engine(eng) == []
        finally:
            eng.stop()


def test_quantized_cache_layout_pinned():
    """The quantized cache's dtypes/shapes are the documented contract:
    int8 values + f32 scale twins shaped values-minus-head_dim, both
    layouts."""
    for layout in ("slot", "paged"):
        eng = make_engine(layout)
        try:
            assert sorted(eng.cache) == ["k", "ks", "v", "vs"]
            for name in ("k", "v"):
                assert eng.cache[name].dtype == jnp.int8
                assert eng.cache[name + "s"].dtype == jnp.float32
                assert (
                    tuple(eng.cache[name + "s"].shape)
                    == tuple(eng.cache[name].shape[:-1])
                )
        finally:
            eng.stop()
