"""ToolStreamParser: incremental tool-call extraction for overlapped
execution, plus the parse_tool_calls fenced-fallback regression.

The load-bearing property: for the wire convention the system prompt
teaches (bare JSON objects, optionally fenced), the stream parser fed any
chunking of the text emits exactly the calls the batch parser extracts
from the finished text — early dispatch moves WHEN execution starts,
never what the conversation records.
"""

import json

from agentcontrolplane_tpu.engine.toolparse import (
    ToolStreamParser,
    parse_tool_calls,
    to_message,
)

CALL1 = '{"name": "web__fetch", "arguments": {"url": "https://x.test/a"}}'
CALL2 = '{"name": "db__query", "arguments": {"sql": "select 1"}}'


def feed_chunks(text, size):
    p = ToolStreamParser()
    out = []
    for i in range(0, len(text), size):
        out.extend(p.feed(text[i : i + size]))
    return p, out


def names_args(calls):
    return [(c.function.name, c.function.arguments) for c in calls]


def test_single_call_one_feed_matches_batch():
    p = ToolStreamParser()
    got = p.feed(CALL1)
    assert names_args(got) == names_args(parse_tool_calls(CALL1))


def test_call_split_at_every_boundary():
    """Chunk the text at EVERY possible split point (the worst decode-block
    boundary): one call in, one call out, identical arguments."""
    for cut in range(1, len(CALL1)):
        p = ToolStreamParser()
        got = p.feed(CALL1[:cut]) + p.feed(CALL1[cut:])
        assert names_args(got) == names_args(parse_tool_calls(CALL1)), cut


def test_multi_token_commit_chunkings_match_batch():
    """Prose + two calls, chunked at sizes mimicking 1-token deltas up to
    speculative multi-token commits — every chunking yields the batch
    parser's calls in order."""
    text = f"I'll do two things.\nFirst: {CALL1}\nthen also {CALL2} — done!"
    want = names_args(parse_tool_calls(text))
    assert len(want) == 2
    for size in (1, 2, 3, 5, 8, 13, 64, len(text)):
        _, got = feed_chunks(text, size)
        assert names_args(got) == want, size


def test_escaped_quotes_and_unicode_escapes_in_arguments():
    call = (
        '{"name": "note__add", "arguments": '
        '{"text": "he said \\"hi\\" \\u00e9\\u0301 {not a call}"}}'
    )
    want = names_args(parse_tool_calls(call))
    assert want and want[0][0] == "note__add"
    for size in (1, 3, 7, len(call)):
        _, got = feed_chunks(call, size)
        assert names_args(got) == want, size
        # the escaped payload survives intact
        assert json.loads(got[0].function.arguments)["text"].startswith('he said "hi"')


def test_python_tag_split_across_deltas():
    """<|python_tag|> is prose to the scanner (no braces): a call after a
    tag split mid-delta parses identically."""
    text = f"<|python_tag|>{CALL1}"
    for cut in (1, 5, 9, 14):  # splits inside the tag
        p = ToolStreamParser()
        got = p.feed(text[:cut]) + p.feed(text[cut:])
        assert names_args(got) == names_args(parse_tool_calls(text)), cut


def test_prose_interleaved_between_calls():
    text = f"step one {CALL1} now, after thinking a bit... step two {CALL2} ok"
    _, got = feed_chunks(text, 4)
    assert [n for n, _ in names_args(got)] == ["web__fetch", "db__query"]


def test_never_closing_brace_bounded_buffering():
    """An object that never closes must not buffer unboundedly: past
    max_object_bytes it is abandoned as prose (dropped counter), and a
    later well-formed call still parses."""
    p = ToolStreamParser(max_object_bytes=256)
    p.feed('{"name": "stuck", "arguments": {"x": "')
    for _ in range(64):
        assert p.feed("a" * 64) == []
    assert p.dropped >= 1
    assert p._buf_len <= 256 + 64  # bounded: candidate was reset
    got = p.feed(f" trailing prose {CALL2}")
    assert names_args(got) == names_args(parse_tool_calls(CALL2))


def test_nested_objects_and_string_arguments_form():
    nested = '{"name": "cfg__set", "arguments": {"obj": {"a": {"b": 1}}}}'
    _, got = feed_chunks(nested, 3)
    assert json.loads(got[0].function.arguments) == {"obj": {"a": {"b": 1}}}
    stringly = '{"name": "t__x", "arguments": "{\\"k\\": 1}"}'
    _, got = feed_chunks(stringly, 5)
    assert got[0].function.arguments == '{"k": 1}'


def test_fenced_block_objects_found_by_scanner():
    text = f'Sure:\n```json\n{CALL1}\n```\nrunning it now'
    _, got = feed_chunks(text, 6)
    assert names_args(got) == names_args(parse_tool_calls(text))


def test_emitted_indices_are_stable():
    p = ToolStreamParser()
    a = p.feed(CALL1)
    b = p.feed(" and " + CALL2)
    assert p.emitted == 2 and len(a) == 1 and len(b) == 1


# -- parse_tool_calls fenced-fallback regression (satellite bugfix) ---------


def test_fenced_block_that_fails_json_falls_back_to_brace_scan():
    """Regression: a fenced block whose whole content fails json.loads
    (prose around the object) used to suppress the balanced-brace fallback
    entirely — the call inside was lost."""
    text = f"```json\nhere is the call:\n{CALL1}\n```"
    calls = parse_tool_calls(text)
    assert names_args(calls) == [
        ("web__fetch", '{"url": "https://x.test/a"}'),
    ]
    msg = to_message(text, allowed_tools={"web__fetch"})
    assert msg.tool_calls and msg.content == ""


def test_fenced_block_with_two_objects_falls_back_and_finds_both():
    text = f"```json\n{CALL1}\n{CALL2}\n```"
    assert [n for n, _ in names_args(parse_tool_calls(text))] == [
        "web__fetch", "db__query",
    ]


def test_parseable_fenced_block_still_takes_precedence():
    """Unchanged rule: when a fence yields a call, bare objects outside
    fences stay prose (defensive against JSON-looking prose)."""
    text = f"```json\n{CALL1}\n```\nand ignore {CALL2} please"
    assert [n for n, _ in names_args(parse_tool_calls(text))] == ["web__fetch"]
