"""LoRA adapters: zero-init equivalence, adapter-only training on a sharded
mesh, and the merge-then-serve path."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from agentcontrolplane_tpu.models.llama import PRESETS, forward, init_params
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.train import LoraConfig, LoraTrainer, init_lora, merge_lora

CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=128, max_seq_len=64)
LORA = LoraConfig(rank=4, alpha=8.0, targets=("wq", "wv", "w1"))


def test_zero_init_merge_is_identity():
    params = init_params(CFG, jax.random.key(0))
    lora = init_lora(CFG, LORA, jax.random.key(1))
    merged = merge_lora(params, lora, LORA)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 16)))
    np.testing.assert_allclose(
        np.asarray(forward(params, toks, CFG)),
        np.asarray(forward(merged, toks, CFG)),
        rtol=1e-6, atol=1e-6,
    )


def test_adapter_training_learns_and_freezes_base():
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    trainer = LoraTrainer(
        config=CFG, lora=LORA, mesh=mesh, optimizer=optax.adam(1e-2)
    )
    base = jax.jit(
        lambda k: init_params(CFG, k), out_shardings=trainer.base_sharding
    )(jax.random.key(0))
    base_snapshot = jax.tree_util.tree_map(np.asarray, base)
    lora_params, opt_state = trainer.init(jax.random.key(1))

    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(1, 128, (4, 32)), dtype=jnp.int32)
    mask = jnp.ones_like(tokens)
    tokens = jax.device_put(tokens, trainer.batch_sharding)
    mask = jax.device_put(mask, trainer.batch_sharding)

    losses = []
    for _ in range(12):
        lora_params, opt_state, loss = trainer.train_step(
            lora_params, opt_state, base, tokens, mask
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses  # overfits the fixed batch

    # the base is FROZEN: bit-identical after training
    for a, b in zip(
        jax.tree_util.tree_leaves(base_snapshot),
        jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, base)),
    ):
        np.testing.assert_array_equal(a, b)

    # and only the targeted layers changed in the merge
    merged = merge_lora(base, lora_params, LORA)
    assert not np.allclose(np.asarray(merged["layers"]["wq"]), base_snapshot["layers"]["wq"])
    np.testing.assert_array_equal(
        np.asarray(merged["layers"]["wo"]), base_snapshot["layers"]["wo"]
    )


def test_merged_adapter_serves():
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    params = init_params(cfg, jax.random.key(0))
    lora_cfg = LoraConfig(rank=4, targets=("wq",))
    lora = init_lora(cfg, lora_cfg, jax.random.key(1))
    # make the delta nonzero so serving actually reflects the adapter
    lora["layers"]["wq"]["b"] = (
        jax.random.normal(jax.random.key(2), lora["layers"]["wq"]["b"].shape) * 0.02
    )
    merged = merge_lora(params, lora, lora_cfg)
    base_eng = Engine(config=cfg, params=params, tokenizer=ByteTokenizer(),
                      mesh=mesh, max_slots=2, max_ctx=128, prefill_buckets=(64, 128))
    lora_eng = Engine(config=cfg, params=merged, tokenizer=ByteTokenizer(),
                      mesh=mesh, max_slots=2, max_ctx=128, prefill_buckets=(64, 128))
    base_eng.start(); lora_eng.start()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        a = base_eng.generate("same prompt", sp).tokens
        b = lora_eng.generate("same prompt", sp).tokens
        assert a != b  # the adapter changed behavior
    finally:
        base_eng.stop(); lora_eng.stop()


def test_lora_save_load_roundtrip(tmp_path):
    from agentcontrolplane_tpu.train import load_lora, save_lora

    lora = init_lora(CFG, LORA, jax.random.key(5))
    lora["layers"]["wq"]["b"] = jnp.ones_like(lora["layers"]["wq"]["b"]) * 0.5
    save_lora(str(tmp_path / "adapter"), lora, LORA, step=3)
    restored, cfg = load_lora(str(tmp_path / "adapter"), CFG)
    assert cfg == LORA
    for a, b in zip(
        jax.tree_util.tree_leaves(lora), jax.tree_util.tree_leaves(restored)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_loader_merges_adapter_host_side(tmp_path):
    """load_safetensors_dir(lora_path=...) merges pre-placement (and
    composes with int8): the served weights must equal an explicit
    merge_lora of the separately loaded base."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    from agentcontrolplane_tpu.engine.weights import load_safetensors_dir
    from agentcontrolplane_tpu.train import save_lora

    hf_config = HFConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=64,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    ckpt = tmp_path / "ckpt"
    LlamaForCausalLM(hf_config).save_pretrained(str(ckpt), safe_serialization=True)

    base, config = load_safetensors_dir(str(ckpt))
    lora_cfg = LoraConfig(rank=4, alpha=8.0, targets=("wq", "w2"))
    lora = init_lora(config, lora_cfg, jax.random.key(1))
    lora["layers"]["wq"]["b"] = (
        jax.random.normal(jax.random.key(2), lora["layers"]["wq"]["b"].shape) * 0.05
    )
    save_lora(str(tmp_path / "adapter"), lora, lora_cfg)

    merged_by_loader, _ = load_safetensors_dir(str(ckpt), lora_path=str(tmp_path / "adapter"))
    expected = merge_lora(base, lora, lora_cfg, compute_dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(merged_by_loader["layers"]["wq"], dtype=np.float32),
        np.asarray(expected["layers"]["wq"], dtype=np.float32),
        rtol=2e-2, atol=2e-2,  # loader merges in f32 then casts to model dtype
    )
    # int8 composes: merged-then-quantized weights serve
    q_params, q_config = load_safetensors_dir(
        str(ckpt), lora_path=str(tmp_path / "adapter"), quantize="int8"
    )
    from agentcontrolplane_tpu.ops.quant import QuantizedTensor

    assert isinstance(q_params["layers"]["wq"], QuantizedTensor)
    toks = jnp.asarray(np.random.default_rng(0).integers(1, 128, (1, 8)), dtype=jnp.int32)
    from agentcontrolplane_tpu.models.llama import forward as fwd

    a = np.asarray(fwd(expected, toks, q_config))
    b = np.asarray(fwd(q_params, toks, q_config))
    assert np.mean(np.argmax(a, -1) == np.argmax(b, -1)) > 0.8


def _tiny_hf_checkpoint(path, vocab=320):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    hf_config = HFConfig(
        vocab_size=vocab, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=128,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    LlamaForCausalLM(hf_config).save_pretrained(str(path), safe_serialization=True)


def test_cli_train_produces_servable_adapter(tmp_path, capsys):
    """acp-tpu train: JSONL (text + messages rows) -> adapter dir; printed
    loss decreases and the adapter merges through the serving loader.
    vocab 320 covers the ByteTokenizer's special ids so the rendered
    messages rows train on real tokens."""
    import json as _json
    import re

    from agentcontrolplane_tpu.cli import main
    from agentcontrolplane_tpu.engine.weights import load_safetensors_dir

    ckpt = tmp_path / "ckpt"
    _tiny_hf_checkpoint(ckpt, vocab=320)
    data = tmp_path / "data.jsonl"
    lines = [{"text": "agents call tools and join results. " * 2}] * 8 + [
        {"messages": [{"role": "user", "content": "hello"},
                      {"role": "assistant", "content": "hi there"}]}
    ] * 4
    data.write_text("\n".join(_json.dumps(d) for d in lines))

    out = tmp_path / "adapter"
    rc = main([
        "train", "--checkpoint", str(ckpt), "--data", str(data),
        "--out", str(out), "--steps", "16", "--batch", "2", "--seq-len", "64",
        "--rank", "4", "--lr", "5e-2",
    ])
    assert rc == 0
    assert (out / "lora.json").exists()
    losses = [
        float(m.group(1))
        for m in re.finditer(r"loss (\d+\.\d+)", capsys.readouterr().out)
    ]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses

    base, _ = load_safetensors_dir(str(ckpt))
    merged, _ = load_safetensors_dir(str(ckpt), lora_path=str(out))
    assert not np.allclose(
        np.asarray(merged["layers"]["wq"], dtype=np.float32),
        np.asarray(base["layers"]["wq"], dtype=np.float32),
    )


def test_cli_train_rejects_bad_dataset_line(tmp_path, capsys):
    from agentcontrolplane_tpu.cli import main

    ckpt = tmp_path / "ckpt"
    _tiny_hf_checkpoint(ckpt)
    data = tmp_path / "bad.jsonl"
    data.write_text('{"text": "fine"}\n{"prompt": "wrong key"}\n')
    rc = main([
        "train", "--checkpoint", str(ckpt), "--data", str(data),
        "--out", str(tmp_path / "a"), "--steps", "1",
    ])
    assert rc == 2
    assert ":2:" in capsys.readouterr().err  # points at the offending line


def test_cli_train_mask_prompt_supervises_assistant_only(tmp_path, capsys):
    """--mask-prompt (default): with a dataset whose user turns are random
    noise but assistant turns are constant, training still converges on the
    assistant span (the supervision mask covers only assistant targets).
    Also: render_turns segments concatenate to the full render."""
    import json as _json

    from agentcontrolplane_tpu.api.resources import Message
    from agentcontrolplane_tpu.cli import main
    from agentcontrolplane_tpu.engine.tokenizer import (
        ByteTokenizer, render_prompt, render_turns,
    )

    msgs = [
        Message(role="system", content="sys"),
        Message(role="user", content="u1"),
        Message(role="assistant", content="a1"),
    ]
    tok = ByteTokenizer()
    joined = "".join(seg for _, seg in render_turns(msgs, []))
    assert render_prompt(msgs, []).startswith(joined)
    flat = []
    for _, seg in render_turns(msgs, []):
        flat.extend(tok.encode(seg))
    assert flat == tok.encode(joined)  # per-segment == whole-string tokens

    ckpt = tmp_path / "ckpt"
    _tiny_hf_checkpoint(ckpt, vocab=320)
    data = tmp_path / "d.jsonl"
    rows = [
        {"messages": [{"role": "user", "content": f"noise {i} {i*7}"},
                      {"role": "assistant", "content": "the answer is tools"}]}
        for i in range(8)
    ]
    data.write_text("\n".join(_json.dumps(r) for r in rows))
    rc = main([
        "train", "--checkpoint", str(ckpt), "--data", str(data),
        "--out", str(tmp_path / "a"), "--steps", "16", "--batch", "2",
        "--seq-len", "64", "--rank", "4", "--lr", "5e-2",
    ])
    assert rc == 0
    import re

    losses = [
        float(m.group(1))
        for m in re.finditer(r"loss (\d+\.\d+)", capsys.readouterr().out)
    ]
    assert losses[-1] < losses[0], losses
