"""Checkpoint loading against real on-disk formats: bf16 tensors (what
Llama-3 checkpoints actually ship, via ml_dtypes under safetensors'
numpy framework), multi-shard directories, and int8 load-time
quantization."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.engine.weights import load_safetensors_dir
from agentcontrolplane_tpu.models.llama import PRESETS, forward


@pytest.fixture(scope="module")
def bf16_sharded_checkpoint(tmp_path_factory):
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    hf_config = HFConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=128,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=128,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(3)
    model = LlamaForCausalLM(hf_config).eval()
    ref_tokens = torch.randint(1, 256, (1, 16))
    with torch.no_grad():
        ref_logits = model(ref_tokens).logits.float().numpy()

    path = tmp_path_factory.mktemp("bf16ckpt")
    # bf16 + forced multi-shard: exactly the wire format of real Llama-3
    model.to(torch.bfloat16).save_pretrained(
        str(path), safe_serialization=True, max_shard_size="100KB"
    )
    return str(path), np.asarray(ref_tokens), ref_logits


def test_bf16_multishard_checkpoint_loads_and_matches(bf16_sharded_checkpoint):
    import os

    path, tokens, ref_logits = bf16_sharded_checkpoint
    shards = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    assert len(shards) > 1, f"fixture must be multi-shard, got {shards}"
    params, config = load_safetensors_dir(path)
    assert config.dim == 64 and config.n_layers == 2
    logits = np.asarray(forward(params, jnp.asarray(tokens), config))
    # bf16 storage: loose tolerance, but argmax must agree
    assert np.mean(np.argmax(logits, -1) == np.argmax(ref_logits, -1)) > 0.9


def test_bf16_checkpoint_int8_quantized_load(bf16_sharded_checkpoint):
    path, tokens, ref_logits = bf16_sharded_checkpoint
    params, config = load_safetensors_dir(path, quantize="int8")
    from agentcontrolplane_tpu.ops.quant import QuantizedTensor

    assert isinstance(params["layers"]["wq"], QuantizedTensor)
    logits = np.asarray(forward(params, jnp.asarray(tokens), config))
    assert np.mean(np.argmax(logits, -1) == np.argmax(ref_logits, -1)) > 0.8
