"""engine/brownout.py: the degradation ladder — controller unit tests
(pressure deltas, one-rung moves, restore hysteresis) plus the engine
apply-seam (stall pressure sheds ``spec_len`` and calm cycles restore
it, with the level mirrored into ``stats()``)."""

from __future__ import annotations

import dataclasses
import time

import jax
import pytest

from agentcontrolplane_tpu.engine.brownout import (
    LADDER,
    BrownoutController,
    BrownoutPolicy,
)
from agentcontrolplane_tpu.engine.engine import PRESETS, Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256,
                          n_kv_heads=2)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- controller (no engine) ---------------------------------------------------


def test_due_gates_on_interval_boundaries():
    bo = BrownoutController(BrownoutPolicy(interval=4))
    fired = [i for i in range(1, 13) if bo.due()]
    assert fired == [4, 8, 12]


def test_pressure_steps_down_one_rung_per_tick():
    """Delta judgment off CUMULATIVE counters, one rung per decision,
    clamped at the ladder depth."""
    bo = BrownoutController()
    assert bo.step(sheds=0, stalls=0) == 0     # baseline tick, no delta
    assert bo.step(sheds=0, stalls=3) == 1     # stall delta -> rung 1
    assert bo.step(sheds=1, stalls=3) == 2     # shed delta counts too
    assert bo.step(sheds=2, stalls=4) == 3
    assert bo.step(sheds=9, stalls=9) == len(LADDER)  # clamped
    assert bo.steps_down == 3
    # an unchanged cumulative counter is calm, not pressure
    assert bo.step(sheds=9, stalls=9) == 3


def test_restore_hysteresis_and_whipsaw_guard():
    """up_after consecutive calm ticks restore one rung; a single
    pressured tick resets the calm streak so a loaded engine never
    whipsaws back into speculative work."""
    bo = BrownoutController(BrownoutPolicy(down_after=2, up_after=2))
    bo.step(0, 0)
    assert bo.step(0, 5) == 0      # pressured #1: not yet
    assert bo.step(0, 9) == 1      # pressured #2: step down
    assert bo.step(0, 9) == 1      # calm #1: not yet
    assert bo.step(0, 10) == 1     # relapse: calm streak resets (and the
    assert bo.step(0, 10) == 1     # down streak restarts); calm #1 again
    assert bo.step(0, 10) == 0     # calm #2: restore
    assert bo.steps_up == 1
    assert bo.step(0, 10) == 0     # floor: never below full service


def test_down_after_streak_requirement():
    bo = BrownoutController(BrownoutPolicy(down_after=2, up_after=1))
    bo.step(0, 0)
    assert bo.step(0, 1) == 0      # pressured #1: not yet
    assert bo.step(0, 1) == 0      # calm: streak resets (and restores n/a)
    assert bo.step(0, 2) == 0      # pressured #1 again
    assert bo.step(0, 3) == 1      # pressured #2: step down


# -- engine apply-seam --------------------------------------------------------


def _wait_for(predicate, timeout_s=30.0, interval_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def test_engine_sheds_and_restores_spec_len_under_stall_pressure():
    """brownout=True + sustained stalls: the engine walks down the
    ladder (saving ``spec_len``), mirrors the level into ``stats()``,
    and walks back up to full service once the throttle budget drains —
    with the saved knob value restored exactly."""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout="paged",
        page_size=8, check_invariants=True,
        brownout=True, brownout_interval=1,
        stall_mult=2.0, stall_min_s=0.02,
    )
    eng.start()
    try:
        orig_spec = eng.spec_len
        sp16 = SamplingParams(temperature=0.0, max_tokens=16)
        # honest post-compile cycles settle the cadence floor first
        eng.submit("warm the cadence floor", sp16).result(timeout=120)
        assert eng.stats()["brownout"] == {
            "enabled": True, "level": 0, "steps_down": 0, "steps_up": 0,
        }
        FAULTS.arm("engine.slow_cycle", times=6, delay_s=0.08)
        slow = eng.submit("sustained pressure",
                          SamplingParams(temperature=0.0, max_tokens=24))
        assert _wait_for(lambda: eng.stats()["brownout"]["level"] >= 1), \
            "stall pressure never stepped the ladder down"
        assert eng.spec_len == 0  # rung 1: speculation off
        slow.result(timeout=180)
        # throttle drained: calm busy cycles walk the ladder back up
        for i in range(12):
            eng.submit(f"calm {i}", SamplingParams(temperature=0.0,
                                                   max_tokens=8)).result(timeout=120)
            if eng.stats()["brownout"]["level"] == 0:
                break
        st = eng.stats()["brownout"]
        assert st["level"] == 0, "ladder never restored full service"
        assert st["steps_down"] >= 1
        assert st["steps_up"] == st["steps_down"]
        assert eng.spec_len == orig_spec  # saved value restored exactly
    finally:
        eng.stop()
