"""Chunked prefill + the unified token-budget scheduler.

The load-bearing guarantee: greedy outputs with chunking ON are
byte-identical to the chunked-off engine across the whole matrix — both KV
layouts, speculation on/off, chunk sizes from 1 to beyond the prompt,
preempt-resume and park-adopt of mid-prefill state — because chunks only
re-shape WHEN prompt KV is written, never what is sampled. The scheduler
policy is pinned too: decode is never starved more than one dispatch by
pending chunks, and prefill always advances at least one chunk per cycle
even under a starvation-sized token budget.

``prefill_chunk``/``token_budget`` are deliberately mutable attributes, so
the identity matrix A/Bs chunk sizes on ONE engine per (layout, spec)
combination instead of building an engine per cell (engines are expensive
to construct on CPU — each compiles its program set).
"""

import contextlib
import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import (
    DeadlineExceededError,
    Engine,
    SamplingParams,
)
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)

# self-similar agent-traffic shape: keeps the n-gram drafter proposing, so
# the spec-on cells of the matrix actually exercise verify dispatches
TOOL_ECHO = '{"tool": "search", "args": {"q": "x"}} {"tool": "search", "args": {"q": "x"}}'


def make_engine(kv_layout="slot", spec_len=0, max_ctx=256, **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    # ACP_INVARIANTS posture for the whole stress suite: every run
    # double-checks the engine's bookkeeping after each dispatch cycle
    kw.setdefault("check_invariants", True)
    kw.setdefault("prefill_buckets", (64, 256))
    kw.setdefault("prefix_cache_entries", 4)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=max_ctx,
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        spec_len=spec_len,
        **kw,
    )
    eng.start()
    return eng


@pytest.fixture(scope="module")
def engines():
    """One engine per (layout, spec) cell; chunk sizes A/B on each."""
    pool = {
        ("slot", 0): make_engine("slot", spec_len=0),
        ("slot", 6): make_engine("slot", spec_len=6),
        ("paged", 0): make_engine("paged", spec_len=0),
        ("paged", 6): make_engine("paged", spec_len=6),
    }
    yield pool
    for eng in pool.values():
        eng.stop()


@contextlib.contextmanager
def chunked(eng, n, budget=0):
    eng.prefill_chunk, eng.token_budget = n, budget
    try:
        yield eng
    finally:
        eng.prefill_chunk, eng.token_budget = 0, 0


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


# -- byte-identity matrix -----------------------------------------------------


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
@pytest.mark.parametrize("spec_len", [0, 6])
def test_greedy_byte_identity_matrix(engines, kv_layout, spec_len):
    """Chunked on vs off, pinned byte-identical: chunk=1 (every token its
    own dispatch; paged rounds to page grain), a mid-size chunk, and
    chunk >= prompt (single-chunk fast path = the plain causal program).
    Prompts cover short (one chunk), long (multi-chunk, beyond a bucket),
    and drafter-friendly repetition so spec cells really speculate."""
    eng = engines[(kv_layout, spec_len)]
    sp = SamplingParams(temperature=0.0, max_tokens=16)
    prompts = ["hello world this is a test", "a" * 150, TOOL_ECHO]
    ref = {p: eng.generate(p, sp).tokens for p in prompts}
    chunks0 = eng.prefill_chunks
    for chunk in (1, 24, 300):
        with chunked(eng, chunk):
            for p in prompts:
                got = eng.generate(p, sp).tokens
                assert got == ref[p], (kv_layout, spec_len, chunk, p[:20])
    assert eng.prefill_chunks > chunks0, "the chunk scheduler must have run"
    if spec_len:
        assert eng.spec_dispatches > 0


def test_chunk_boundary_at_ctx_edge():
    """Budget-edge regression: prompts landing the final chunk boundary AT
    max_ctx-1 (a context-filling prompt leaves a 1-token budget) and one
    token short of it must clip at exactly the same token chunked on/off."""
    eng = make_engine(max_ctx=96, prefill_buckets=(32, 96), prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=64)
        for plen in (94, 93, 89):
            prompt = [1 + (i % 250) for i in range(plen)]
            ref = eng.generate(prompt, sp)
            for chunk in (31, 32, plen - 1):
                with chunked(eng, chunk):
                    got = eng.generate(prompt, sp)
                assert got.tokens == ref.tokens, (plen, chunk)
                assert got.finish_reason == ref.finish_reason
    finally:
        eng.stop()


# -- scheduler policy ---------------------------------------------------------


def test_decode_never_starved_and_chunks_always_progress():
    """The two policy guarantees: (a) while any slot decodes, every
    scheduler cycle that dispatches prefill chunks also dispatches decode
    (decode is never starved more than one dispatch by pending chunks);
    (b) a starvation-sized token budget (1 token vs a 4-wide decode
    reserve) still advances at least one chunk per cycle — the long prompt
    completes instead of deadlocking."""
    eng = make_engine(prefix_cache_entries=0)
    try:
        events: list[tuple[int, int]] = []  # (decode_steps, n_active) per chunk cycle
        real_chunks, real_decode = eng._prefill_chunks, eng._decode_once

        def spy_chunks(budget):
            spent = real_chunks(budget)
            if spent:
                events.append((eng.decode_steps, eng._n_active()))
            return spent

        eng._prefill_chunks = spy_chunks
        # the repetition attractor decodes long (>60 tokens on this seed —
        # pinned by test_spec_decode), so decode lanes stay live while the
        # long prompt's ~25 chunks trickle through the 1-token budget
        decoder = eng.submit(
            TOOL_ECHO, SamplingParams(temperature=0.0, max_tokens=80)
        )
        ok = decoder.admitted.result(timeout=180)
        assert ok
        deadline = time.monotonic() + 180
        while eng.decode_steps == 0 and time.monotonic() < deadline:
            time.sleep(0.005)  # decoding, not just admitted
        with chunked(eng, 8, budget=1):
            long = eng.submit("z" * 200, SamplingParams(temperature=0.0, max_tokens=4))
            long.result(timeout=180)
        decoder.result(timeout=180)
        eng._prefill_chunks = real_chunks
        # consecutive chunk cycles with a decode lane live must be separated
        # by decode progress (decode is never starved more than one dispatch)
        live_pairs = [
            (a, b)
            for (a, act_a), (b, act_b) in zip(events, events[1:])
            if act_a and act_b
        ]
        assert len(live_pairs) >= 3, (events, "decoder died before the chunks ran")
        for a, b in live_pairs:
            assert b > a, "decode starved across a chunk-only cycle"
    finally:
        eng.stop()


def test_deadline_expires_between_chunks_releases_partial_kv():
    eng = make_engine("paged", prefix_cache_entries=0)
    try:
        # this test exercises the mid-prefill EXPIRY machinery, so the
        # chunk-rate planner must not rescue the deadline (with it on, a
        # 0.15s deadline gets a quota-sized chunk that finishes in time —
        # the arithmetic the planner exists for), and each chunk cycle is
        # slowed deterministically so a warm compile cache can't finish
        # the 200-token prefill inside the deadline either
        eng.rate_planner = False
        real_chunks = eng._prefill_chunks

        def slow_chunks(budget):
            time.sleep(0.02)
            return real_chunks(budget)

        eng._prefill_chunks = slow_chunks
        free0 = eng._allocator.free_count
        expired0 = counter("acp_engine_deadline_expired_total")
        with chunked(eng, 1):
            fut = eng.submit(
                "z" * 200, SamplingParams(temperature=0.0, max_tokens=8),
                timeout_s=0.15,
            )
            with pytest.raises(DeadlineExceededError, match="mid-prefill"):
                fut.result(timeout=120)
        deadline = time.monotonic() + 10
        while eng._prefilling_count and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng._prefilling_count == 0
        assert len(eng._free) == eng.max_slots
        assert eng._allocator.free_count == free0, "partial KV pages leaked"
        assert counter("acp_engine_deadline_expired_total") == expired0 + 1
        # the engine still serves
        r = eng.generate("ok", SamplingParams(temperature=0.0, max_tokens=4))
        assert r.tokens
    finally:
        eng.stop()


# -- preemption / park-adopt of mid-prefill state -----------------------------


def test_preempt_mid_prefill_fault_byte_identity():
    """The dedicated fault site lands preemption on a partially-prefilled
    slot; the request requeues, re-enters the chunk loop, and the greedy
    output is byte-identical — with speculation on, paged layout."""
    eng = make_engine("paged", spec_len=6, prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        ref = eng.generate("y" * 150, sp)
        pre0 = eng.preemptions
        with chunked(eng, 24):
            FAULTS.arm(
                "engine.preempt_mid_prefill", times=1,
                after_steps=eng.prefill_chunks + 2,
            )
            got = eng.generate("y" * 150, sp)
        assert got.tokens == ref.tokens
        assert got.preempt_count == 1
        assert eng.preemptions == pre0 + 1
    finally:
        eng.stop()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_park_adopt_across_chunked_prefill(kv_layout):
    """A parked slot adopted by the conversation's next turn while chunking
    is on: the suffix re-enters the chunk loop at the park cut and the
    output matches a fresh chunked-off generation of the same prompt."""
    eng = make_engine(kv_layout, prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        turn1 = "sys prompt: be an agent. " + "abc" * 20
        turn2 = turn1 + " user: more more more"
        ref2 = eng.generate(turn2, sp).tokens
        with chunked(eng, 16):
            eng.submit(turn1, sp, park=True).result(timeout=120)
            a0 = eng.park_adoptions
            got2 = eng.generate(turn2, sp).tokens
        assert eng.park_adoptions == a0 + 1, "the next turn must adopt the park"
        assert got2 == ref2
    finally:
        eng.stop()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_spec_verify_leaves_parked_prompt_kv_intact(kv_layout):
    """Regression for the verify-dispatch lane defaults: lanes NOT in a
    speculative dispatch (parked, mid-prefill, free) used to scatter one
    garbage K/V row into position 0 of their LIVE state — corrupting a
    parked slot's prompt KV, visible the moment the next turn adopts it
    while another slot keeps verify dispatches flowing."""
    eng = make_engine(kv_layout, spec_len=6, prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        turn1 = "agent sys. " + "abc" * 15
        turn2 = turn1 + " user: go go go"
        ref2 = eng.generate(turn2, sp).tokens
        decoder = eng.submit(
            TOOL_ECHO, SamplingParams(temperature=0.0, max_tokens=120)
        )
        eng.submit(turn1, sp, park=True).result(timeout=120)
        time.sleep(0.5)  # verify dispatches run with the parked lane present
        a0 = eng.park_adoptions
        got2 = eng.generate(turn2, sp).tokens
        decoder.result(timeout=180)
        assert eng.park_adoptions == a0 + 1
        assert got2 == ref2, "parked prompt KV was corrupted by a verify dispatch"
    finally:
        eng.stop()


def test_stress_page_pressure_spec_and_mid_prefill_preempt():
    """The combined stress the fault site exists for: an oversubscribed
    paged pool under injected page pressure, speculation on, chunked
    prefill on, and a forced mid-prefill preemption — every output still
    byte-identical to the uncontended chunked-off engine."""
    eng = make_engine("paged", spec_len=6, prefix_cache_entries=0, kv_pages=60)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        prompts = ["p" * 120, "q" * 90, "r" * 60]
        refs = [eng.generate(p, sp).tokens for p in prompts]
        with chunked(eng, 16):
            FAULTS.arm("engine.page_pressure", pages=12)
            FAULTS.arm(
                "engine.preempt_mid_prefill", times=1,
                after_steps=eng.prefill_chunks + 1,
            )
            futs = [eng.submit(p, sp) for p in prompts]
            got = [f.result(timeout=300).tokens for f in futs]
        assert got == refs
    finally:
        eng.stop()


def test_toggle_off_mid_prefill_drains_page_aligned():
    """Toggling prefill_chunk to 0 while a paged slot is mid-prefill must
    drain it through the chunk loop at the largest bucket — collapsing to
    1-token chunks would tear the page-aligned whole-page-commit invariant
    (earlier prompt KV rewritten with garbage) and crawl in slot layout."""
    eng = make_engine("paged", prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        ref = eng.generate("t" * 200, sp).tokens
        eng.prefill_chunk = 16
        fut = eng.submit("t" * 200, sp)
        deadline = time.monotonic() + 60
        while not eng._prefilling_count and time.monotonic() < deadline:
            time.sleep(0.002)
        eng.prefill_chunk = 0  # mid-flight toggle: must drain, not corrupt
        assert fut.result(timeout=180).tokens == ref
    finally:
        eng.stop()


# -- observability ------------------------------------------------------------


def test_scheduler_stats_and_metrics(engines):
    eng = engines[("slot", 0)]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    chunks0 = counter("acp_engine_prefill_chunks_total")
    with chunked(eng, 8):
        eng.generate("m" * 100, sp)
        s = eng.stats()
    assert s["scheduler"]["chunked_prefill"] is True
    assert s["scheduler"]["prefill_chunk"] == 8
    assert s["scheduler"]["prefill_chunks_total"] == eng.prefill_chunks
    assert 0.0 <= s["scheduler"]["budget_utilization_avg"] <= 1.0
    assert counter("acp_engine_prefill_chunks_total") > chunks0
    assert "prefilling_slots" in s and s["prefilling_slots"] == 0


def test_hol_wait_attributed_while_decoding(engines):
    """The HOL metric moves in BOTH modes when a prefill runs while slots
    decode — that shared definition is what makes the chunked-on/off bench
    comparison meaningful."""
    eng = engines[("slot", 0)]
    # the repetition attractor decodes its full 60-token budget (pinned by
    # test_spec_decode on this seed), so the decoder is still live when the
    # second prompt's admission prefill dispatches — a short greedy prompt
    # could stop before it and make the stall attribution vacuously flaky
    steps0 = eng.decode_steps
    decoder = eng.submit(TOOL_ECHO, SamplingParams(temperature=0.0, max_tokens=60))
    deadline = time.monotonic() + 120
    while eng.decode_steps == steps0 and time.monotonic() < deadline:
        time.sleep(0.002)  # decoding, not just admitted
    h0 = eng.hol_wait_s
    eng.generate("n" * 150, SamplingParams(temperature=0.0, max_tokens=4))
    decoder.result(timeout=180)
    assert eng.hol_wait_s > h0
    assert counter("acp_engine_hol_wait_seconds") > 0
