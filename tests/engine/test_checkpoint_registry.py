"""Orbax checkpoint roundtrip (sharded restore) + external API registry."""

import dataclasses

import numpy as np
import pytest

import jax
import optax

from agentcontrolplane_tpu.externalapi import Registry, register_defaults
from agentcontrolplane_tpu.kernel.errors import Invalid
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.train.checkpoint import (
    abstract_like,
    restore_checkpoint,
    save_checkpoint,
)
from agentcontrolplane_tpu.train.trainer import Trainer

TINY = PRESETS["tiny"]


def test_checkpoint_roundtrip_sharded(tmp_path):
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 2}, devices=jax.devices()[:2])
    trainer = Trainer(config=TINY, mesh=mesh, optimizer=optax.adam(1e-3))
    params, opt_state = trainer.init(jax.random.key(0))
    tokens, mask = trainer.shard_batch(
        np.random.default_rng(0).integers(0, TINY.vocab_size, size=(2, 16))
    )
    params, opt_state, loss = trainer.train_step(params, opt_state, tokens, mask)

    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(ckpt, params, opt_state, step=1)

    abstract = {
        "params": abstract_like(params, trainer.param_sharding),
        "opt_state": abstract_like(opt_state, trainer.opt_sharding),
    }
    restored = restore_checkpoint(ckpt, abstract)
    r_params = restored["params"]
    np.testing.assert_array_equal(
        np.asarray(r_params["norm"]), np.asarray(params["norm"])
    )
    # restored leaves carry the requested shardings
    leaf = r_params["layers"]["wq"]
    assert leaf.sharding == trainer.param_sharding["layers"]["wq"]
    # training continues from the restored state
    p2, o2, loss2 = trainer.train_step(
        r_params, restored["opt_state"], tokens, mask
    )
    assert np.isfinite(float(loss2))


def test_registry_resolves_secret_and_unknown_errors(store):
    from tests.fixtures import make_secret

    reg = Registry()
    seen = {}

    def factory(key):
        seen["key"] = key
        return f"client:{key}"

    reg.register("svc", factory)
    make_secret(store, "creds", {"token": "tok-123"})
    from agentcontrolplane_tpu.api.resources import SecretKeyRef

    client = reg.get_client(
        "svc", store=store, key_ref=SecretKeyRef(name="creds", key="token")
    )
    assert client == "client:tok-123"
    assert seen["key"] == "tok-123"
    with pytest.raises(Invalid, match="no external API client"):
        reg.get_client("ghost")


def test_register_defaults_has_humanlayer():
    reg = register_defaults(Registry())
    assert "humanlayer" in reg.registered()
