"""Regenerate the golden fidelity assets in this directory.

The real Llama-3 ``tokenizer.json`` cannot be downloaded in this environment
(zero egress), so fidelity is proven on a tokenizer with the IDENTICAL
structure — the same byte-level BPE pipeline the Llama-3 checkpoint ships:

- the cl100k-family pre-tokenization split regex Llama-3 uses,
- ByteLevel alphabet (GPT-2 bytes<->unicode table), ByteLevel decoder,
- the full Llama-3 special-token set (``<|begin_of_text|>`` etc.),
- BPE merges trained on a deterministic corpus (small vocab).

Golden vectors and chat-template renders are produced through HF
``transformers``' ``PreTrainedTokenizerFast`` + ``apply_chat_template`` with
the official Llama-3 Jinja template — the independent implementation our
``HFTokenizer`` + ``render_prompt`` must match token-for-token. Swapping in
a real downloaded ``tokenizer.json`` exercises the exact same code path;
the download is the only untested step (VERDICT r2 missing #5).

Run: ``python tests/engine/golden/build_goldens.py`` (writes to its own dir).
"""

from __future__ import annotations

import json
import os
import pathlib

HERE = pathlib.Path(__file__).parent

# Llama-3's pre-tokenization split pattern (tiktoken cl100k_base family, as
# carried in the checkpoint's tokenizer.json pre_tokenizer config).
LLAMA3_SPLIT = (
    r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}|"
    r" ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
)

# Llama-3 special tokens (the serving-relevant subset of the 128000+ block).
SPECIALS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|eot_id|>",
    "<|python_tag|>",
]

# The official Llama-3 chat template (base conversation form: header turns,
# trimmed content, generation prompt) as shipped in tokenizer_config.json.
LLAMA3_CHAT_TEMPLATE = (
    "{{ bos_token }}"
    "{% for message in messages %}"
    "{{ '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n' }}"
    "{{ message['content'] | trim }}{{ '<|eot_id|>' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}"
    "{% endif %}"
)

# Encode/decode probe strings: ascii, contractions (split-regex behavior),
# digit grouping, unicode (CJK, emoji, combining marks), whitespace runs,
# newline runs, specials embedded mid-text, and empty-ish edges.
PROBES = [
    "hello world",
    "Hello, World! It's Claude's 3rd try -- isn't it?",
    "    leading and trailing    ",
    "tabs\tand\nnewlines\r\n\r\nand more",
    "numbers 1 22 333 4444 55555 3.14159",
    "日本語のテキストと中文文本",
    "emoji 🙂🚀 and ½ fractions ®",
    "combining á ë marks",
    "camelCaseIdentifiers and snake_case_names and kebab-case-names",
    'JSON {"name": "fetch", "arguments": {"url": "https://x.test/a?b=c&d=e"}}',
    "<|begin_of_text|>raw specials<|eot_id|> mid text<|end_of_text|>",
    "a",
    " ",
    "\n\n",
    "mixed 英語 and English words 123",
]

CHAT_CASES = [
    [
        {"role": "system", "content": "You are a helpful assistant."},
        {"role": "user", "content": "What is the capital of France?"},
    ],
    [
        {"role": "user", "content": "  whitespace around content  "},
        {"role": "assistant", "content": "Trimmed reply.\n"},
        {"role": "user", "content": "next\n\nquestion"},
    ],
    [
        {"role": "system", "content": "Be terse."},
        {"role": "user", "content": "hi"},
        {"role": "assistant", "content": "hello"},
        {"role": "user", "content": "日本語で答えて 🙂"},
    ],
]


def build_tokenizer() -> "object":
    from tokenizers import Regex, Tokenizer, decoders, models, pre_tokenizers, trainers

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.Sequence(
        [
            pre_tokenizers.Split(Regex(LLAMA3_SPLIT), behavior="isolated"),
            pre_tokenizers.ByteLevel(add_prefix_space=False, use_regex=False),
        ]
    )
    tok.decoder = decoders.ByteLevel()

    # fully self-contained deterministic corpus — no filesystem reads, so
    # regeneration from any location reproduces the assets byte-for-byte
    corpus: list[str] = list(PROBES) * 3
    corpus += [
        "the quick brown fox jumps over the lazy dog " * 50,
        "The operator reconciles tasks, tool calls, agents and language "
        "models through phase state machines stored with optimistic "
        "concurrency. " * 20,
        "def tokenize(text):\n    return [ord(c) for c in text]\n" * 20,
        "continuous batching shards key value caches over tensor parallel "
        "meshes while ring attention streams long contexts " * 20,
    ]

    trainer = trainers.BpeTrainer(
        vocab_size=2048,
        special_tokens=SPECIALS,
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(corpus, trainer)
    return tok


def main() -> None:
    tok = build_tokenizer()
    tok_path = HERE / "tokenizer.json"
    tok.save(str(tok_path))

    from transformers import PreTrainedTokenizerFast

    hf = PreTrainedTokenizerFast(
        tokenizer_file=str(tok_path),
        bos_token="<|begin_of_text|>",
        eos_token="<|end_of_text|>",
        chat_template=LLAMA3_CHAT_TEMPLATE,
    )

    vectors = [
        {
            "text": s,
            "ids": hf.encode(s, add_special_tokens=False),
            "decoded": hf.decode(
                hf.encode(s, add_special_tokens=False), skip_special_tokens=False
            ),
        }
        for s in PROBES
    ]
    (HERE / "vectors.json").write_text(json.dumps(vectors, indent=1, ensure_ascii=False))

    chats = [
        {
            "messages": msgs,
            "rendered": hf.apply_chat_template(
                msgs, tokenize=False, add_generation_prompt=True
            ),
            "ids": hf.apply_chat_template(msgs, tokenize=True, add_generation_prompt=True),
        }
        for msgs in CHAT_CASES
    ]
    (HERE / "chat_goldens.json").write_text(
        json.dumps(chats, indent=1, ensure_ascii=False)
    )
    print(f"wrote {tok_path}, vectors.json ({len(vectors)}), chat_goldens.json ({len(chats)})")


if __name__ == "__main__":
    main()
