"""Model correctness: logits vs HF transformers; prefill+decode vs full
forward; GQA/rope/sampling unit checks. All on CPU with the tiny preset."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.models.llama import (
    PRESETS,
    LlamaConfig,
    decode_step,
    forward,
    init_kv_cache,
    init_params,
    prefill,
)
from agentcontrolplane_tpu.engine.weights import params_from_state_dict

TINY = PRESETS["tiny"]


@pytest.fixture(scope="module")
def hf_model_and_params():
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    hf_config = HFConfig(
        vocab_size=TINY.vocab_size,
        hidden_size=TINY.dim,
        num_hidden_layers=TINY.n_layers,
        num_attention_heads=TINY.n_heads,
        num_key_value_heads=TINY.n_kv_heads,
        intermediate_size=TINY.ffn_dim,
        rms_norm_eps=TINY.norm_eps,
        rope_theta=TINY.rope_theta,
        max_position_embeddings=TINY.max_seq_len,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_config).eval()
    params = params_from_state_dict(model.state_dict(), TINY)
    return model, params


def test_logits_match_hf_reference(hf_model_and_params):
    """Our forward must agree with transformers' LlamaForCausalLM — this is
    the correctness anchor for the whole serving stack."""
    import torch

    model, params = hf_model_and_params
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, TINY.vocab_size, size=(2, 17))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, dtype=jnp.int32), TINY))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_remat_gradients_match_non_remat():
    """jax.checkpoint on the layer-scan body (Trainer remat=True default)
    must change MEMORY, never math: loss and every gradient leaf equal to
    the non-remat backward. Lives here (no device-count skipif) so the
    guarantee is verified everywhere, not only under the 8-device mesh
    harness."""
    import dataclasses

    from agentcontrolplane_tpu.train.trainer import lm_loss

    cfg = dataclasses.replace(TINY, vocab_size=128, dtype=jnp.float32)
    params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(1, 128, size=(2, 16)), dtype=jnp.int32)
    mask = jnp.ones((2, 16), dtype=jnp.float32)

    def loss(remat):
        return jax.value_and_grad(
            lambda p: lm_loss(p, tokens, mask, cfg, remat=remat)
        )(params)

    loss_plain, grads_plain = loss(False)
    loss_remat, grads_remat = loss(True)
    assert float(loss_plain) == pytest.approx(float(loss_remat), rel=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(grads_plain), jax.tree_util.tree_leaves(grads_remat)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_llama31_rope_scaling_matches_hf():
    """Llama-3.1/3.2 checkpoints ship rope_scaling (rope_type 'llama3');
    serving them with unscaled frequencies computes a different function
    than they were trained with. Pin our scaled-rope forward against
    transformers' implementation, with positions far enough past the
    'original' context that all three frequency branches matter."""
    import dataclasses

    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers import LlamaForCausalLM

    scaled = dataclasses.replace(
        TINY,
        max_seq_len=256,
        rope_scaling_factor=8.0,
        rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0,
        rope_original_max_seq=32,  # tiny, so T=100 is deep into scaled range
    )
    hf_config = HFConfig(
        vocab_size=scaled.vocab_size,
        hidden_size=scaled.dim,
        num_hidden_layers=scaled.n_layers,
        num_attention_heads=scaled.n_heads,
        num_key_value_heads=scaled.n_kv_heads,
        intermediate_size=scaled.ffn_dim,
        rms_norm_eps=scaled.norm_eps,
        rope_theta=scaled.rope_theta,
        max_position_embeddings=scaled.max_seq_len,
        tie_word_embeddings=False,
        attn_implementation="eager",
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
    )
    torch.manual_seed(7)
    model = LlamaForCausalLM(hf_config).eval()
    params = params_from_state_dict(model.state_dict(), scaled)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, scaled.vocab_size, size=(1, 100))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, dtype=jnp.int32), scaled))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)
    # and the scaling genuinely changes the function (guards against the
    # scaling silently not being applied on either side)
    unscaled = dataclasses.replace(scaled, rope_scaling_factor=1.0)
    ours_unscaled = np.asarray(
        forward(params, jnp.asarray(tokens, dtype=jnp.int32), unscaled)
    )
    assert np.max(np.abs(ours - ours_unscaled)) > 1e-3


def test_prefill_matches_forward(hf_model_and_params):
    _, params = hf_model_and_params
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(11,)), dtype=jnp.int32)
    full = forward(params, prompt[None], TINY)[0]  # [T, V]

    cache = init_kv_cache(TINY, max_slots=4, max_ctx=32)
    padded = jnp.pad(prompt, (0, 5))  # padded prompt
    cache, logits = prefill(params, cache, padded, jnp.int32(11), jnp.int32(2), TINY)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full[-1]), rtol=2e-4, atol=2e-4
    )
    # cache rows for slot 2 are populated, others untouched
    assert np.abs(np.asarray(cache["k"][0, 2, :11])).sum() > 0
    assert np.abs(np.asarray(cache["k"][0, 0])).sum() == 0


def test_decode_steps_match_full_forward(hf_model_and_params):
    """Prefill then N decode steps must reproduce the logits of a single
    full-sequence forward — the serving loop is exact, not approximate."""
    _, params = hf_model_and_params
    rng = np.random.default_rng(2)
    seq = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(16,)), dtype=jnp.int32)
    split = 10
    full = forward(params, seq[None], TINY)[0]  # [16, V]

    S, C = 3, 32
    cache = init_kv_cache(TINY, max_slots=S, max_ctx=C)
    slot = 1
    padded = jnp.pad(seq[:split], (0, C - split))
    cache, logits = prefill(params, cache, padded, jnp.int32(split), jnp.int32(slot), TINY)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[split - 1]), rtol=2e-4, atol=2e-4)

    seq_lens = jnp.zeros((S,), dtype=jnp.int32)
    for t in range(split, 16):
        tokens = jnp.zeros((S,), dtype=jnp.int32).at[slot].set(seq[t])
        lens = seq_lens.at[slot].set(t)
        cache, step_logits = decode_step(params, cache, tokens, lens, TINY)
        np.testing.assert_allclose(
            np.asarray(step_logits[slot]), np.asarray(full[t]), rtol=3e-4, atol=3e-4
        )


def test_decode_slots_are_independent(hf_model_and_params):
    """Continuous batching invariant: computing a token for slot A must not
    perturb slot B's cache or logits."""
    _, params = hf_model_and_params
    rng = np.random.default_rng(3)
    S, C = 2, 32
    a = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(8,)), dtype=jnp.int32)
    b = jnp.asarray(rng.integers(0, TINY.vocab_size, size=(5,)), dtype=jnp.int32)

    # batch both slots together
    cache = init_kv_cache(TINY, max_slots=S, max_ctx=C)
    cache, _ = prefill(params, cache, jnp.pad(a, (0, C - 8)), jnp.int32(8), jnp.int32(0), TINY)
    cache, _ = prefill(params, cache, jnp.pad(b, (0, C - 5)), jnp.int32(5), jnp.int32(1), TINY)
    tokens = jnp.asarray([a[-1], b[-1]], dtype=jnp.int32)  # dummy next tokens
    lens = jnp.asarray([8, 5], dtype=jnp.int32)
    _, batched_logits = decode_step(params, cache, tokens, lens, TINY)

    # slot 1 alone
    cache1 = init_kv_cache(TINY, max_slots=S, max_ctx=C)
    cache1, _ = prefill(params, cache1, jnp.pad(b, (0, C - 5)), jnp.int32(5), jnp.int32(1), TINY)
    tokens1 = jnp.asarray([0, b[-1]], dtype=jnp.int32)
    lens1 = jnp.asarray([0, 5], dtype=jnp.int32)
    _, solo_logits = decode_step(params, cache1, tokens1, lens1, TINY)

    np.testing.assert_allclose(
        np.asarray(batched_logits[1]), np.asarray(solo_logits[1]), rtol=2e-4, atol=2e-4
    )


def test_tied_embeddings_head():
    config = LlamaConfig(
        vocab_size=64, dim=32, n_layers=1, n_heads=2, n_kv_heads=1,
        ffn_dim=64, tie_embeddings=True, dtype=jnp.float32, rope_theta=10000.0,
    )
    params = init_params(config, jax.random.key(0))
    assert "lm_head" not in params
    logits = forward(params, jnp.zeros((1, 4), dtype=jnp.int32), config)
    assert logits.shape == (1, 4, 64)


def test_sampling_modes():
    from agentcontrolplane_tpu.ops.sampling import sample

    logits = jnp.asarray(
        [[1.0, 2.0, 3.0, 0.5], [10.0, 0.0, 0.0, 0.0]], dtype=jnp.float32
    )
    rng = jax.random.key(0)
    # greedy (temperature 0)
    out = sample(
        logits, rng,
        temperature=jnp.asarray([0.0, 0.0]),
        top_k=jnp.asarray([0, 0], dtype=jnp.int32),
        top_p=jnp.asarray([1.0, 1.0]),
    )
    assert out.tolist() == [2, 0]
    # top_k=1 equals greedy even at high temperature
    out = sample(
        logits, rng,
        temperature=jnp.asarray([5.0, 5.0]),
        top_k=jnp.asarray([1, 1], dtype=jnp.int32),
        top_p=jnp.asarray([1.0, 1.0]),
    )
    assert out.tolist() == [2, 0]
    # tight top_p keeps only the argmax bucket
    out = sample(
        logits, rng,
        temperature=jnp.asarray([1.0, 1.0]),
        top_k=jnp.asarray([0, 0], dtype=jnp.int32),
        top_p=jnp.asarray([0.2, 0.2]),
    )
    assert out.tolist() == [2, 0]
    # sampled tokens always within vocab and from allowed set
    keys = jax.random.split(jax.random.key(1), 50)
    for k in keys[:10]:
        out = sample(
            logits, k,
            temperature=jnp.asarray([1.0, 1.0]),
            top_k=jnp.asarray([2, 2], dtype=jnp.int32),
            top_p=jnp.asarray([1.0, 1.0]),
        )
        assert out[0].item() in (1, 2)


def test_blocked_causal_attention_matches_dense():
    """The flash-style blocked prefill attention is exact vs the dense path
    (incl. padded rows and GQA)."""
    import numpy as np

    from agentcontrolplane_tpu.ops.attention import (
        blocked_causal_attention,
        causal_attention,
    )

    rng = np.random.default_rng(0)
    B, T, H, Hkv, d = 2, 256, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    lengths = np.asarray([256, 100])
    ar = np.arange(T)
    positions = jnp.asarray(
        np.where(ar[None] < lengths[:, None], ar[None], -1), dtype=jnp.int32
    )
    dense = causal_attention(q, k, v, positions)
    blocked = blocked_causal_attention(q, k, v, positions, block_size=64)
    valid = np.asarray(positions) >= 0
    np.testing.assert_allclose(
        np.asarray(blocked)[valid], np.asarray(dense)[valid], rtol=2e-5, atol=2e-5
    )
    # non-divisible T falls back to dense (still exact)
    odd = blocked_causal_attention(q[:, :200], k[:, :200], v[:, :200],
                                   positions[:, :200], block_size=64)
    np.testing.assert_allclose(
        np.asarray(odd)[valid[:, :200]],
        np.asarray(causal_attention(q[:, :200], k[:, :200], v[:, :200], positions[:, :200]))[valid[:, :200]],
        rtol=2e-5, atol=2e-5,
    )
