"""Async host-KV prefetch (ISSUE 20): the stage/commit split of swap-in.

The load-bearing guarantees:

- **Byte identity, prefetch on vs off** — the prefetcher only changes
  WHEN the host->device restore copies happen (a cycle early, overlapped
  with compute), never what lands in the pages, so greedy output under an
  oversubscribed pool is bit-identical with ``host_prefetch`` on or off.
- **The overlap actually happens** — multi-chunk restores commit staged
  rows (``acp_engine_kv_prefetch_commits_total``), not blocking copies.
- **Graceful degradation** — an ``engine.prefetch_error``-aborted stage
  (and any stale stage) falls back to the blocking copy byte-identically,
  recording a ``prefetch_abort`` flight event.
- **Megastep absorption** — on a fused paged cycle the staged scatter
  rides the megastep as its swaps phase (an ``s...`` part in the fused
  program key) instead of dispatching standalone.
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.invariants import verify_engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    kw.setdefault("prefix_cache_entries", 0)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout="paged",
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def _settle(eng: Engine) -> None:
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (eng._has_work() or len(eng._waiting)):
        time.sleep(0.01)
    time.sleep(0.1)


def _pressure_run(eng):
    """Oversubscribed pool: preemptions swap KV out and resumes swap it
    back in over several chunked cycles while survivors keep decoding —
    the workload where prefetch has something to overlap with."""
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    prompts = [ch * 20 for ch in "abcdef"]
    solo = {p: eng.generate(p, sp).tokens for p in prompts}
    with eng.hold_admission():
        futs = [eng.submit(p, sp) for p in prompts]
    results = {p: f.result(timeout=300) for p, f in zip(prompts, futs)}
    for p, r in results.items():
        assert r.tokens == solo[p], f"swap round-trip diverged for {p!r}"
    return [results[p].tokens for p in prompts]


def test_prefetch_on_off_byte_identity_and_overlap_counted():
    outs = {}
    for pf in (False, True):
        before = counter("acp_engine_kv_prefetch_commits_total")
        eng = make_engine(
            kv_pages=10, host_kv_bytes=1 << 22, prefill_chunk=16,
            host_prefetch=pf,
        )
        try:
            outs[pf] = _pressure_run(eng)
            assert eng.kv_swap_ins >= 1, "no swap round-trip formed"
            committed = (
                counter("acp_engine_kv_prefetch_commits_total") - before
            )
            if pf:
                assert committed > 0, "prefetch never staged a commit"
            else:
                assert committed == 0, "host_prefetch=False still staged"
            _settle(eng)
            assert verify_engine(eng) == []
        finally:
            eng.stop()
    assert outs[True] == outs[False], "prefetch changed sampled bytes"


def test_prefetch_error_degrades_to_blocking_copy_identically():
    eng = make_engine(kv_pages=10, host_kv_bytes=1 << 22, prefill_chunk=16)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcdef"]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        FAULTS.arm("engine.prefetch_error", times=2)
        with eng.hold_admission():
            futs = [eng.submit(p, sp) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=300).tokens == solo[p], (
                f"prefetch abort diverged for {p!r}"
            )
        aborts = eng.flight.events(kind="prefetch_abort")
        assert aborts, "armed engine.prefetch_error never fired"
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_staged_scatter_absorbs_into_megastep_swaps_phase():
    """A restore chunk committing while other slots decode must ride the
    fused program (an ``s...`` part in a megastep key) rather than
    dispatch its scatter standalone."""
    eng = make_engine(
        kv_pages=10, host_kv_bytes=1 << 22, prefill_chunk=16, megastep=True,
    )
    try:
        _pressure_run(eng)
        _settle(eng)
        keys = eng.profiler.stats()["programs"]
        fused_swap = [
            k for k in keys
            if k.startswith("megastep[") and ",s" in k.replace("+s", ",s")
        ]
        assert fused_swap, f"no fused swaps-phase program key in {sorted(keys)}"
        assert verify_engine(eng) == []
    finally:
        eng.stop()
