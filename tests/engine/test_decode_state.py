"""Device-resident decode state (clean-block carry reuse) equivalence.

The decode loop only re-uploads host mirrors on dirty blocks
(admission/finish/cancel); between those, per-slot state chains through
the jitted block's carry with finish detection on device. These tests pin
the riskiest property: a workload full of staggered admissions, mid-stream
joins, early stops, and cancels must generate EXACTLY the same tokens as
the same engine forced to re-upload state every block (the pre-rework
behavior, emulated by dirtying the flag before each block).
"""

import dataclasses
import threading

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TINY = dataclasses.replace(PRESETS["tiny"], max_seq_len=128)


def _build(kv_layout: str) -> Engine:
    return Engine(
        config=TINY,
        tokenizer=ByteTokenizer(),
        max_slots=4,
        max_ctx=128,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout=kv_layout,
        seed=0,
        mesh=make_mesh({"tp": 1}, devices=jax.devices()[:1]),
    )


def _force_dirty_every_block(eng: Engine) -> None:
    orig = eng._decode_once

    def dirty_then_decode():
        eng._state_dirty = True
        orig()

    eng._decode_once = dirty_then_decode


def _staggered_workload(eng: Engine) -> list[list[int]]:
    """Greedy generations with DETERMINISTICALLY staggered arrivals: the
    engine loop is driven manually (no thread, no sleeps) so both engines
    see identical admission points, block boundaries, and dispatch widths
    — exact token equality across runs is then a sound assertion."""
    eng._thread = threading.main_thread()  # white-box: satisfy submit()

    def step(n: int) -> None:
        for _ in range(n):
            eng._admit(block=False)
            if eng._slots:
                eng._decode_once()

    futs = []
    # wave 1: two requests join together, then decode clean blocks
    for i in range(2):
        futs.append(
            eng.submit(
                [1 + i] * (20 + 3 * i),
                SamplingParams(temperature=0.0, max_tokens=24 + 5 * i),
            )
        )
    step(3)
    # wave 2: mid-stream join (admission dirty) + a short one that
    # finishes early (finish dirty) while wave 1 is still decoding
    futs.append(eng.submit([9] * 40, SamplingParams(temperature=0.0, max_tokens=30)))
    futs.append(eng.submit([5] * 8, SamplingParams(temperature=0.0, max_tokens=3)))
    step(2)
    # a cancel processed at a fixed block boundary
    doomed = eng.submit([7] * 16, SamplingParams(temperature=0.0, max_tokens=64))
    step(1)
    eng.cancel(doomed)
    for _ in range(100):
        if all(f.done() for f in futs) and doomed.done():
            break
        step(1)
    out = [f.result(timeout=0).tokens for f in futs]
    assert doomed.done()
    return out


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_clean_block_reuse_matches_forced_upload(kv_layout):
    fresh = _staggered_workload(_build(kv_layout))
    forced = _build(kv_layout)
    _force_dirty_every_block(forced)
    assert _staggered_workload(forced) == fresh
    assert all(len(t) > 0 for t in fresh)


def test_ctx_edge_generates_to_the_last_token():
    """A slot near max_ctx decodes to exactly max_ctx-1 (device-side
    deactivation), not to the next block boundary short of it."""
    eng = _build("slot")
    eng.start()
    try:
        # max_tokens=28 keeps submit's tail-truncation off (reserve=28,
        # budget=100, prompt exactly fits). The ctx edge stops the slot:
        # 1 prefill-sampled token + 27 decode steps walks seq from 100 to
        # 127 (max_ctx-1), then the device deactivates the lane
        prompt = [3] * 100
        out = eng.generate(prompt, SamplingParams(temperature=0.0, max_tokens=28))
    finally:
        eng.stop()
    stops = set(eng.tokenizer.stop_tokens)
    if not (set(out.tokens) & stops):
        assert len(out.tokens) == 28, len(out.tokens)
        assert out.finish_reason == "length"
