"""Fused megastep dispatch (ISSUE 13):

- byte-identity: megastep on vs off (the split per-phase dispatches) with
  chunked prefill + speculative decoding + park/adopt all active, both KV
  layouts, under the armed invariant checker — the load-bearing contract;
- ONE dispatch per steady-state busy cycle, asserted via the PR 12
  profiler's program keys: while mid-prefill chunks co-run with decode,
  the only model program dispatching is ``megastep[...]``;
- the shape bound: a new fused shape past ``megastep_max_programs`` falls
  back to the split programs (outputs still byte-identical) and counts
  ``megastep_fallbacks``;
- the goodput ledger's fused-program waste row (``pad_fuse``) stays
  conserved (audited every cycle by the armed checker — these engines all
  run with it on);
- the megastep prewarm phase forms the core fused shapes (and records the
  standard ``prewarm_gap`` event + counter when one cannot form).
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)
# repetition attractor: the n-gram drafter proposes on it, so spec cells
# really speculate (same trick as test_spec_decode)
ATTRACTOR = "abcabcabc " * 8


def make_engine(kv_layout="slot", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    kw.setdefault("prefix_cache_entries", 0)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=128,
        prefill_buckets=(32, 64, 128),
        width_buckets=(1, 2, 4),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str, **labels) -> float:
    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    return m.values.get(tuple(sorted(labels.items())), 0.0)


def _busy_run(eng):
    """A busy mixed workload: a long-decoding anchor plus long prompts
    chunking through it (plus a short latecomer), so cycles carry
    mid-chunks, continuation finals and decode/verify together."""
    sp_long = SamplingParams(temperature=0.0, max_tokens=30)
    anchor = eng.submit(ATTRACTOR, sp_long)
    assert anchor.admitted.result(timeout=120)
    deadline = time.monotonic() + 120
    while eng.decode_steps == 0 and time.monotonic() < deadline:
        time.sleep(0.002)
    futs = [
        eng.submit("the quick brown fox jumps over " * 4,
                   SamplingParams(temperature=0.0, max_tokens=10)),
        eng.submit("pack my box with five dozen jugs " * 3,
                   SamplingParams(temperature=0.0, max_tokens=10)),
        eng.submit("hello small prompt", SamplingParams(temperature=0.0, max_tokens=8)),
    ]
    return [f.result(timeout=300).tokens for f in [anchor, *futs]]


# -- byte identity ------------------------------------------------------------


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
@pytest.mark.parametrize("spec_len", [0, 4])
def test_megastep_byte_identity_busy_matrix(kv_layout, spec_len):
    """Fused vs split vs unchunked: the same mixed busy workload must emit
    bit-for-bit identical tokens. Chunked prefill + spec active; armed
    invariant checker audits every cycle (incl. ledger conservation with
    the new pad_fuse row)."""
    outs = {}
    for mode, (mega, chunk) in {
        "split": (False, 16),
        "fused": (True, 16),
    }.items():
        eng = make_engine(kv_layout, spec_len=spec_len, megastep=mega,
                          prefill_chunk=chunk)
        try:
            outs[mode] = _busy_run(eng)
            if mode == "fused":
                assert eng.megastep_dispatches > 0, "fused path never ran"
                fused_keys = [
                    k for k in eng.profiler.stats()["programs"]
                    if k.startswith("megastep[")
                ]
                assert fused_keys, "no megastep program keys recorded"
        finally:
            eng.stop()
    # THE load-bearing contract: fused == split, bit for bit. (Chunked vs
    # UNCHUNKED identity is pinned sequentially in test_chunked_prefill;
    # under CONCURRENT load the cycle composition differs between those
    # two modes and the tiny random model's exact argmax ties can flip —
    # the known program-shape nondeterminism class, orthogonal to fusion.
    # Fused vs split runs the identical schedule, so it must be exact.)
    assert outs["fused"] == outs["split"], (kv_layout, spec_len)


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_megastep_byte_identity_with_park_adopt(kv_layout):
    """Two-turn conversation with park-on-finish: turn 2 adopts the parked
    slot (suffix-only continuation) while chunked + fused. Joined output
    must match the unchunked, unfused engine."""
    turn1 = "persona prompt " * 4
    turn2 = turn1 + " and then some follow up words"
    sp = SamplingParams(temperature=0.0, max_tokens=12)

    def run(mega, chunk):
        eng = make_engine(kv_layout, megastep=mega, prefill_chunk=chunk)
        try:
            r1 = eng.submit(turn1, sp, park=True).result(timeout=180)
            r2 = eng.submit(turn2, sp).result(timeout=180)
            return r1.tokens, r2.tokens, eng.park_adoptions
        finally:
            eng.stop()

    t1_ref, t2_ref, _ = run(False, 0)
    t1, t2, adoptions = run(True, 12)
    assert (t1, t2) == (t1_ref, t2_ref)
    assert adoptions >= 1, "turn 2 never adopted the parked slot"


@pytest.mark.parametrize("mega", [False, True])
def test_inactive_lane_decode_write_clamps_to_unread_row(mega):
    """LATENT BUG pinned (found by the fused matrix, but reachable in the
    split path too): the slot layout's decode block used to write one
    garbage K/V row per INACTIVE lane at that lane's uploaded seq_len.
    With a mid-prefill slot BELOW an active slot (here: slot 0 freed by a
    finished request, re-used by a chunking long prompt while slot 1 still
    decodes, so the dispatch width covers lane 0), a not-dirty decode
    block's garbage landed inside prompt rows the chunk loop had already
    written — silently corrupting the prefill. Inactive lanes must clamp
    their write to the never-readable last row (the paged layout always
    masked to TRASH_PAGE). Pinned in both dispatch modes."""
    import numpy as np

    prompt_c = "a curious llama wanders the andes " * 3
    plen = len(TOK.encode(prompt_c))
    sp_c = SamplingParams(temperature=0.0, max_tokens=10)

    def prompt_rows(eng, tokens_out):
        # slot 0's prompt KV rows [1, plen) — row 0 excluded (a free lane's
        # zeroed mirror legally parks pre-fix garbage there), rows beyond
        # the prompt excluded (decode writes them)
        k = np.asarray(eng.cache["k"][:, 0, 1:plen])
        v = np.asarray(eng.cache["v"][:, 0, 1:plen])
        return k, v, tokens_out

    # reference: the SAME chunked engine mode with no neighbour decoding —
    # same continuation programs write the prompt rows, no adjacent lane
    # to spray garbage
    ref_eng = make_engine("slot", megastep=mega, prefill_chunk=16)
    try:
        ref = ref_eng.generate(prompt_c, sp_c).tokens
        deadline = time.monotonic() + 60
        while ref_eng._has_work() and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)
        rk, rv, _ = prompt_rows(ref_eng, ref)
    finally:
        ref_eng.stop()
    eng = make_engine("slot", megastep=mega, prefill_chunk=16)
    try:
        # A takes slot 0 and decodes long enough for B to land in slot 1;
        # A then finishes, and C re-uses freed slot 0: mid-prefill BELOW
        # the active lane — the dispatch width now covers C's lane
        a = eng.submit("short lived", SamplingParams(temperature=0.0, max_tokens=16))
        assert a.admitted.result(timeout=120)
        b = eng.submit(ATTRACTOR, SamplingParams(temperature=0.0, max_tokens=60))
        assert b.admitted.result(timeout=120)
        a.result(timeout=120)
        deadline = time.monotonic() + 120
        while eng.decode_steps == 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        c = eng.submit(prompt_c, sp_c)
        got = c.result(timeout=300).tokens
        b.result(timeout=300)
        # the hazard topology must have formed (C below B), or the test
        # proves nothing — locate C's slot from its flight admit event
        c_slot = next(
            e["slot"] for e in eng.flight.events(kind="prefill_done")
            if e["detail"].get("seq") == plen
        )
        assert c_slot == 0, f"topology failed to form: C landed in slot {c_slot}"
        # read the cache only once the engine is idle (an in-flight
        # dispatch donates it)
        deadline = time.monotonic() + 60
        while eng._has_work() and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)
        gk, gv, _ = prompt_rows(eng, got)
        assert got == ref, (mega, got, ref)
        bad = np.where(~np.isclose(gk, rk).all(axis=(0, 2, 3)))[0]
        assert bad.size == 0, f"prompt KV rows corrupted at {1 + bad} (mega={mega})"
        assert np.allclose(gv, rv)
    finally:
        eng.stop()


# -- one dispatch per steady-state busy cycle ---------------------------------


def test_steady_state_busy_cycle_is_one_dispatch():
    """THE acceptance criterion: while mid-prefill chunks co-run with
    decode, every model program dispatched is the fused megastep — the
    split chunk/decode/verify/continuation programs dispatch ZERO times in
    the window (asserted via profiler program keys)."""
    eng = make_engine("paged", prefill_chunk=8)
    try:
        anchor = eng.submit(ATTRACTOR, SamplingParams(temperature=0.0, max_tokens=40))
        assert anchor.admitted.result(timeout=120)
        deadline = time.monotonic() + 120
        while eng.decode_steps == 0 and time.monotonic() < deadline:
            time.sleep(0.002)

        def split_dispatches():
            progs = eng.profiler.stats()["programs"]
            return {
                k: v["dispatches"] for k, v in progs.items()
                if k.split("[")[0] in
                ("chunk", "decode", "spec_verify", "prefill_cont", "spill")
            }

        def fused_dispatches():
            progs = eng.profiler.stats()["programs"]
            return sum(
                v["dispatches"] for k, v in progs.items()
                if k.startswith("megastep[")
            )

        # settle into the busy window: a long prompt starts chunking while
        # the anchor decodes
        long = eng.submit("w" * 110, SamplingParams(temperature=0.0, max_tokens=6))
        assert long.admitted.result(timeout=120)
        deadline = time.monotonic() + 120
        while not eng._prefilling_count and time.monotonic() < deadline:
            time.sleep(0.001)
        before_split = split_dispatches()
        before_fused = fused_dispatches()
        # the busy window: chunks + decode co-scheduled
        while eng._prefilling_count and time.monotonic() < deadline:
            time.sleep(0.001)
        after_split = split_dispatches()
        after_fused = fused_dispatches()
        assert after_fused > before_fused, "no fused dispatches in the window"
        # the split per-phase programs stayed silent: fused cycles paid
        # exactly one dispatch each. (decode[] may resume AFTER the window
        # — once nothing is mid-prefill the plain block is already one
        # dispatch — so the comparison is within the window only.)
        assert after_split == before_split, (before_split, after_split)
        long.result(timeout=180)
        anchor.result(timeout=180)
    finally:
        eng.stop()


# -- shape bound fallback -----------------------------------------------------


@pytest.mark.parametrize("spec_len", [0, 4])
def test_shape_bound_falls_back_to_split_programs(spec_len):
    """megastep_max_programs=0: every fused shape is over the bound, so
    every fused cycle split-dispatches (fallback counter rises) and the
    output is still byte-identical. spec_len=4 pins the verify-path
    fallback specifically: the standalone verify after the fallback's
    chunk dispatches must re-capture self.cache (the fallback donated the
    one its args snapshot held — a stale-buffer crash pre-fix)."""
    ref = make_engine("slot", megastep=False, prefill_chunk=16,
                      spec_len=spec_len)
    try:
        want = _busy_run(ref)
    finally:
        ref.stop()
    eng = make_engine("slot", megastep=True, prefill_chunk=16,
                      spec_len=spec_len, megastep_max_programs=0)
    try:
        fb0 = counter("acp_engine_megastep_fallbacks_total")
        got = _busy_run(eng)
        assert got == want
        assert eng.megastep_dispatches == 0
        assert eng.megastep_fallbacks > 0
        assert counter("acp_engine_megastep_fallbacks_total") > fb0
        assert not any(
            k.startswith("megastep[") for k in eng.profiler.stats()["programs"]
        )
    finally:
        eng.stop()


# -- pad_fuse accounting ------------------------------------------------------


def test_pad_fuse_waste_row_populates_and_conserves():
    """Three concurrent long prompts form a 3-lane mid phase padded to 4:
    the fused-program waste row (pad_fuse) must populate, and the ledger
    must stay conserved (the armed checker also audits this per cycle)."""
    eng = make_engine("paged", prefill_chunk=8)
    try:
        anchor = eng.submit(ATTRACTOR, SamplingParams(temperature=0.0, max_tokens=36))
        assert anchor.admitted.result(timeout=120)
        deadline = time.monotonic() + 120
        while eng.decode_steps == 0 and time.monotonic() < deadline:
            time.sleep(0.002)
        futs = [
            eng.submit(c * 100, SamplingParams(temperature=0.0, max_tokens=4))
            for c in "uvw"
        ]
        for f in [anchor, *futs]:
            f.result(timeout=300)
        led = eng.profiler.ledger()
        assert led["computed"] == led["goodput"] + sum(led["waste"].values())
        assert led["waste"]["pad_fuse"] > 0, led["waste"]
    finally:
        eng.stop()


# -- prewarm coverage ---------------------------------------------------------


def test_prewarm_megastep_forms_fused_shapes():
    eng = make_engine("slot", prefill_chunk=16)
    try:
        gaps0 = counter("acp_engine_prewarm_gaps_total", phase="megastep")
        eng._prewarm_megastep(constrained=False)
        # the core fused shape (chunk bucket, B=1) formed — or the gap was
        # recorded as data; on this tiny config it must form
        assert any(
            any(p.startswith("m32x1") for p in sh[1])
            for sh in eng._megastep_shapes
        ), eng._megastep_shapes
        assert counter("acp_engine_prewarm_gaps_total", phase="megastep") == gaps0
    finally:
        eng.stop()


def test_prewarm_megastep_gap_is_recorded():
    eng = make_engine("slot", prefill_chunk=16)
    try:
        # poison the verification surface so no planned shape can verify:
        # every attempt exhausts and records the standard prewarm gap
        class _Never(set):
            def add(self, item):
                pass

        eng._megastep_shapes = _Never()
        gaps0 = counter("acp_engine_prewarm_gaps_total", phase="megastep")
        eng._prewarm_megastep(constrained=False)
        assert counter("acp_engine_prewarm_gaps_total", phase="megastep") > gaps0
        gaps = eng.flight.events(kind="prewarm_gap")
        assert any(e["detail"].get("phase") == "megastep" for e in gaps)
    finally:
        eng.stop()
