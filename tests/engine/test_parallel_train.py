"""Sharding correctness on the virtual 8-device CPU mesh: ring attention vs
dense reference, TP-sharded forward vs single-device forward, and the full
dp/sp/tp train step."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from agentcontrolplane_tpu.models.llama import PRESETS, forward, init_params
from agentcontrolplane_tpu.ops.attention import causal_attention
from agentcontrolplane_tpu.parallel.mesh import make_mesh, param_shardings
from agentcontrolplane_tpu.parallel.ring_attention import ring_causal_attention
from agentcontrolplane_tpu.train.trainer import Trainer

TINY = PRESETS["tiny"]

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def test_ring_attention_matches_dense():
    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 2})
    B, T, H, Hkv, d = 2, 32, 4, 2, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))

    dense = causal_attention(q, k, v, positions)
    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else _nullcontext():
        ring = ring_causal_attention(mesh, q, k, v, positions)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), rtol=1e-5, atol=1e-5)


def test_ring_attention_with_padding_positions():
    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 2})
    B, T, H, Hkv, d = 1, 16, 4, 2, 8
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    # last 6 positions are padding (-1)
    positions = jnp.asarray(
        [[0, 1, 2, 3, 4, 5, 6, 7, 8, 9, -1, -1, -1, -1, -1, -1]], dtype=jnp.int32
    )
    dense = causal_attention(q, k, v, positions)
    ring = ring_causal_attention(mesh, q, k, v, positions)
    # compare only valid positions (padding rows are garbage in both)
    np.testing.assert_allclose(
        np.asarray(ring)[:, :10], np.asarray(dense)[:, :10], rtol=1e-5, atol=1e-5
    )


def test_tp_sharded_forward_matches_single_device():
    """The same logits must come out of the TP=8-sharded forward as from an
    unsharded one — XLA's inserted collectives are semantics-preserving."""
    mesh = make_mesh({"tp": 8})
    cfg = dataclasses.replace(TINY, n_kv_heads=8 if TINY.n_heads >= 8 else TINY.n_kv_heads)
    # tiny has 4 heads / 2 kv heads; tp=8 can't divide heads -> use tp=2 mesh
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    params = init_params(TINY, jax.random.key(0))
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
    base = forward(params, tokens, TINY)

    shardings = param_shardings(mesh, TINY, params)
    sharded_params = jax.tree_util.tree_map(jax.device_put, params, shardings)
    sharded_logits = jax.jit(lambda p, t: forward(p, t, TINY))(sharded_params, tokens)
    np.testing.assert_allclose(
        np.asarray(sharded_logits), np.asarray(base), rtol=2e-4, atol=2e-4
    )


def test_train_step_dp_tp_loss_decreases():
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2}, devices=jax.devices()[:4])
    trainer = Trainer(
        config=TINY, mesh=mesh, optimizer=optax.adam(1e-3), sequence_parallel=False
    )
    params, opt_state = trainer.init(jax.random.key(0))
    rng = np.random.default_rng(0)
    tokens, mask = trainer.shard_batch(rng.integers(0, TINY.vocab_size, size=(4, 32)))
    losses = []
    for _ in range(5):
        params, opt_state, loss = trainer.train_step(params, opt_state, tokens, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch
    assert np.isfinite(losses).all()


def test_train_step_sequence_parallel_matches_dense():
    """One train step with ring-attention sp=2 must produce the same loss as
    the dense dp-only step (exact attention, just distributed)."""
    mesh_sp = make_mesh({"dp": 1, "sp": 2, "tp": 2}, devices=jax.devices()[:4])
    mesh_dense = make_mesh({"dp": 1, "sp": 1, "tp": 2}, devices=jax.devices()[:4][:2])
    rng = np.random.default_rng(0)
    batch = rng.integers(0, TINY.vocab_size, size=(2, 32))

    t_sp = Trainer(config=TINY, mesh=mesh_sp, optimizer=optax.sgd(1e-2), sequence_parallel=True)
    t_dn = Trainer(config=TINY, mesh=mesh_dense, optimizer=optax.sgd(1e-2))
    p_sp, o_sp = t_sp.init(jax.random.key(7))
    p_dn, o_dn = t_dn.init(jax.random.key(7))

    tok_sp, m_sp = t_sp.shard_batch(batch)
    tok_dn, m_dn = t_dn.shard_batch(batch)
    p_sp, o_sp, loss_sp = t_sp.train_step(p_sp, o_sp, tok_sp, m_sp)
    p_dn, o_dn, loss_dn = t_dn.train_step(p_dn, o_dn, tok_dn, m_dn)
    np.testing.assert_allclose(float(loss_sp), float(loss_dn), rtol=1e-4)
    # params after the step agree too
    np.testing.assert_allclose(
        np.asarray(p_sp["norm"]), np.asarray(p_dn["norm"]), rtol=1e-4, atol=1e-5
    )


class _nullcontext:
    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


def test_ring_attention_gradients_match_dense():
    """Backward through the ring (ppermute + online softmax) must produce
    the same input gradients as dense attention — sp fine-tuning is exact."""
    mesh = make_mesh({"dp": 1, "sp": 4, "tp": 2})
    B, T, H, Hkv, d = 1, 16, 4, 2, 8
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(B, T, H, d)), dtype=jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, Hkv, d)), dtype=jnp.float32)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    cotangent = jnp.asarray(rng.normal(size=(B, T, H, d)), dtype=jnp.float32)

    def loss_dense(q, k, v):
        return jnp.sum(causal_attention(q, k, v, positions) * cotangent)

    def loss_ring(q, k, v):
        return jnp.sum(ring_causal_attention(mesh, q, k, v, positions) * cotangent)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4, err_msg=name
        )
