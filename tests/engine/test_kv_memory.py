"""KV memory tiers: host-RAM offload + cross-request shared-prefix dedup.

The load-bearing guarantees (acceptance criteria for the memory hierarchy):

- **Swap round-trip byte identity** — a preempted/park-expired request
  whose KV swapped to host RAM and back produces bit-identical greedy
  output vs the knobs-off (discard-and-recompute) path, in both KV
  layouts, with speculation on, across preempt-resume and park-adopt.
- **Dedup byte identity** — refcount-shared prompt pages (a burst of
  same-persona requests) never change what is sampled; they only change
  how many physical copies of the prefix exist.
- **Graceful degradation** — every swap failure (pool off, pool full,
  injected ``engine.host_swap_slow`` / ``engine.host_swap_error``) falls
  back to recompute, still byte-identically, with the armed invariant
  checker auditing every dispatch cycle throughout.
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.invariants import verify_engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    # armed posture for the whole suite: every dispatch cycle audits the
    # three pools (HBM pages, host entries, shared refcounts)
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def _settle(eng: Engine) -> None:
    """Wait for the engine loop to drain to idle so test-thread audits
    don't race a dispatch cycle (memory mirrors publish per cycle)."""
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (eng._has_work() or len(eng._waiting)):
        time.sleep(0.01)
    time.sleep(0.1)


# -- host-RAM offload tier: swap round-trip byte identity --------------------


def test_swap_roundtrip_identical_paged_under_pool_pressure():
    """Oversubscribed paged pool: preemptions swap KV to host and resume
    swaps it back — outputs equal the uncontended runs exactly, and at
    least one full swap round-trip is observed."""
    eng = make_engine(kv_pages=10, host_kv_bytes=1 << 22)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcdef"]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        before_out = counter("acp_engine_kv_swap_out_total")
        with eng.hold_admission():
            futs = [eng.submit(p, sp) for p in prompts]
        results = dict(zip(prompts, (f.result(timeout=180) for f in futs)))
        for p, r in results.items():
            assert r.tokens == solo[p], f"swap round-trip diverged for {p!r}"
            assert r.finish_reason in ("stop", "length")
        assert eng.kv_swap_outs >= 1 and eng.kv_swap_ins >= 1
        assert counter("acp_engine_kv_swap_out_total") > before_out
        mem = eng.stats()["memory"]["host_kv"]
        assert mem["enabled"] and mem["swap_ins"] == eng.kv_swap_ins
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_forced_preempt_swap_resume_identical_spec_on(kv_layout):
    """Both layouts, speculation on: a forced preemption swaps out, the
    resume swaps in, and greedy output matches the unpreempted run."""
    eng = make_engine(kv_layout=kv_layout, host_kv_bytes=1 << 22, spec_len=4)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        base = eng.generate("hello world " * 4, sp).tokens
        FAULTS.arm("engine.force_preempt", after_steps=2)
        r = eng.generate("hello world " * 4, sp)
        assert r.tokens == base
        assert r.preempt_count >= 1
        assert eng.kv_swap_outs >= 1 and eng.kv_swap_ins >= 1
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_swap_in_metered_through_chunked_budget_loop():
    """With chunked prefill on, a swap-in restores through the token-budget
    scheduler (budget-costed chunks) — byte-identical, and the restore's
    chunks are flight-recorded as swap chunks."""
    eng = make_engine(
        kv_pages=10, host_kv_bytes=1 << 22, prefill_chunk=16,
        prefix_cache_entries=0,
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcdef"]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        with eng.hold_admission():
            futs = [eng.submit(p, sp) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=180).tokens == solo[p]
        assert eng.kv_swap_ins >= 1
        swap_chunks = eng.flight.events(
            last=0, kind="prefill_chunk"
        )
        assert any(e.get("detail", {}).get("swap") for e in swap_chunks)
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_park_expiry_swaps_and_prefix_match_restores():
    """A parked slot expiring swaps its prompt KV to host; the
    conversation's next turn (different rid) restores it by token-prefix
    match instead of re-prefilling — byte-identical to a cold run."""
    eng = make_engine(
        kv_pages=60, host_kv_bytes=1 << 22, park_max_s=0.2,
        prefix_cache_entries=0,
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        turn1 = "persona " * 5
        cold = eng.generate(turn1 + "more text here", sp).tokens
        eng.submit(turn1, sp, park=True).result(60)
        deadline = time.monotonic() + 10
        while eng.kv_swap_outs < 1 and time.monotonic() < deadline:
            # keep the loop spinning so the park-expiry sweep runs
            eng.submit("x", SamplingParams(temperature=0.0, max_tokens=1)).result(30)
            time.sleep(0.02)
        assert eng.kv_swap_outs >= 1, "park expiry never swapped out"
        r = eng.generate(turn1 + "more text here", sp)
        assert r.tokens == cold
        assert eng.kv_swap_ins >= 1
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_host_tier_off_is_todays_behavior():
    """host_kv_bytes=0 (the default): no pool, no swap events, no host
    bytes — the preempt path is exactly the discard-and-recompute engine."""
    eng = make_engine(kv_pages=10)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcd"]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        with eng.hold_admission():
            futs = [eng.submit(p, sp) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=180).tokens == solo[p]
        assert eng.preemptions >= 1
        assert eng.kv_swap_outs == 0 and eng.kv_swap_ins == 0
        assert eng.stats()["memory"]["host_kv"]["enabled"] is False
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_host_pool_budget_bounds_and_lru_evicts():
    """A pool too small for every victim stays within budget (LRU) and
    oversized entries are refused — resumes still byte-identical."""
    # budget fits roughly one tiny entry: 2 layers * 2 heads * 64 dim *
    # 2B * 2 (k+v) = 1KiB/row -> 16 rows/page = ~16KiB per page
    eng = make_engine(kv_pages=10, host_kv_bytes=40 * 1024)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcdef"]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        with eng.hold_admission():
            futs = [eng.submit(p, sp) for p in prompts]
        for p, f in zip(prompts, futs):
            assert f.result(timeout=180).tokens == solo[p]
        assert eng._host_pool.used_bytes <= eng.host_kv_bytes
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


# -- cross-request shared-prefix dedup ---------------------------------------


PERSONA = "p" * 40
TAILS = [f"-{chr(97 + i) * 4}" for i in range(4)]


@pytest.mark.parametrize("prefill_chunk", [0, 16])
def test_dedup_burst_identical_and_shares_pages(prefill_chunk):
    """A burst of same-persona requests admitted in one group shares the
    persona's pages (1 copy, not N) and produces byte-identical outputs —
    with and without chunked prefill (the mid-prefill-leader wait path)."""
    eng = make_engine(
        kv_pages=40, prefix_cache_entries=0, prefill_chunk=prefill_chunk,
        spec_len=4,
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        solo = {t: eng.generate(PERSONA + t, sp).tokens for t in TAILS}
        with eng.hold_admission():
            futs = [eng.submit(PERSONA + t, sp) for t in TAILS]
        for t, f in zip(TAILS, futs):
            assert f.result(timeout=180).tokens == solo[t], f"dedup diverged {t!r}"
        assert eng.prefix_shares >= len(TAILS) - 1
        share_events = eng.flight.events(last=0, kind="prefix_share")
        assert len(share_events) >= len(TAILS) - 1
        assert all(e["detail"]["pages"] >= 1 for e in share_events)
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_dedup_multiplies_concurrent_slots_at_fixed_page_budget():
    """The capacity claim: at a pool too small for N private persona
    copies, dedup admits the whole burst concurrently (shared prefix
    pages), where dedup-off serializes it. Sizing: persona 48 tokens = 6
    pages; each private row needs ~8 pages incl. decode growth, so 4
    private copies (32) exceed the 23 usable pages while the shared form
    (6 + 4x2) fits."""
    persona = "q" * 48

    def peak_concurrency(dedup: bool) -> int:
        eng = make_engine(
            kv_pages=24, prefix_cache_entries=0, prefix_dedup=dedup,
            park_max_s=0.0,
        )
        try:
            sp = SamplingParams(temperature=0.0, max_tokens=8)
            streaming: set = set()
            peak = [0]

            def on_tokens(i):
                def cb(_toks):
                    streaming.add(i)
                    live = eng.stats()
                    peak[0] = max(
                        peak[0], live["active_slots"] + live["prefilling_slots"]
                    )
                return cb

            with eng.hold_admission():
                futs = [
                    eng.submit(persona + t, sp, on_tokens=on_tokens(i))
                    for i, t in enumerate(TAILS)
                ]
            for f in futs:
                f.result(timeout=180)
            return peak[0]
        finally:
            eng.stop()

    with_dedup = peak_concurrency(True)
    without = peak_concurrency(False)
    # persona = 5 pages/request private vs 1 shared copy: the 39-page pool
    # (one trash page) fits all 4 shared but not 4 private + lookahead
    assert with_dedup >= len(TAILS), (with_dedup, without)
    assert with_dedup > without, (with_dedup, without)


def test_dedup_off_never_shares():
    eng = make_engine(kv_pages=40, prefix_cache_entries=0, prefix_dedup=False)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        with eng.hold_admission():
            futs = [eng.submit(PERSONA + t, sp) for t in TAILS]
        for f in futs:
            f.result(timeout=180)
        assert eng.prefix_shares == 0
        assert eng.stats()["memory"]["prefix_dedup"]["enabled"] is False
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_parked_dedup_leader_released_for_capacity_admits_undeduped():
    """When the ONLY parked capacity IS the chosen dedup leader, the
    engine must release it for its slot id and admit the request
    undeduped — not crash the dispatch thread resolving the vanished
    leader's pages. (The leader's prompt shares a persona prefix with the
    request but is not a strict prefix of it, so adoption can't apply.)"""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=1, max_ctx=128,
        prefill_buckets=(32, 64, 128), decode_block_size=4,
        kv_layout="paged", page_size=8, prefix_cache_entries=0,
        check_invariants=True, park_max_s=30.0,
    )
    eng.start()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        persona = "persona " * 4  # 32 shared tokens
        other = persona + "-- a different task entirely"
        solo = eng.generate(other, sp).tokens
        eng.submit(persona + "conversation one", sp, park=True).result(60)
        assert eng._parked_count == 1
        r = eng.submit(other, sp).result(timeout=120)  # pre-fix: engine crash
        assert r.tokens == solo
        assert eng.park_releases >= 1
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_dedup_leader_preempted_mid_prefill_followers_recover():
    """A dedup leader preempted mid-prefill rewinds its waiting followers
    to the rows it actually wrote; everyone still finishes byte-identical
    (the follower recomputes the gap into the shared pages)."""
    eng = make_engine(kv_pages=40, prefix_cache_entries=0, prefill_chunk=8)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        solo = {t: eng.generate(PERSONA + t, sp).tokens for t in TAILS}
        FAULTS.arm("engine.preempt_mid_prefill", after_steps=1)
        with eng.hold_admission():
            futs = [eng.submit(PERSONA + t, sp) for t in TAILS]
        for t, f in zip(TAILS, futs):
            assert f.result(timeout=180).tokens == solo[t], (
                f"follower diverged after leader preemption: {t!r}"
            )
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_dedup_follower_of_adopted_leader_shares_full_page_list():
    """A follower whose dedup leader is a just-ADOPTED parked slot in the
    same admission group must share the leader's FULL page list (kept +
    fresh), not the parked slot's stale kept-only list — a truncated
    share maps rows between the park cut and the share cut to
    never-written follower pages and decodes over garbage KV."""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=128,
        prefill_buckets=(32, 64, 128), decode_block_size=4,
        kv_layout="paged", page_size=8, prefix_cache_entries=0,
        check_invariants=True, park_max_s=30.0,
    )
    eng.start()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        turn1 = "persona " * 6  # 48 tokens -> park_cut 48
        next_turn = turn1 + "assistant said; next question"  # row 77, cut 72
        solo = eng.generate(next_turn, sp).tokens
        eng.submit(turn1, sp, park=True).result(60)
        with eng.hold_admission():  # A adopts; B dedups on A past the cut
            fa = eng.submit(next_turn, sp)
            fb = eng.submit(next_turn, sp)
        ra, rb = fa.result(timeout=120), fb.result(timeout=120)
        assert eng.park_adoptions >= 1 and eng.prefix_shares >= 1
        assert ra.tokens == solo
        assert rb.tokens == solo, (
            "follower of an adopted leader decoded over unwritten rows"
        )
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_mid_restore_preempt_reputs_whole_entry():
    """A slot preempted WHILE its swap-in is restoring must re-put the
    whole consumed host entry (zero copy) — not just the rows that landed
    — so the next resume still swaps in instead of recomputing."""
    eng = make_engine(
        kv_pages=12, host_kv_bytes=1 << 22, prefill_chunk=8,
        prefix_cache_entries=0,
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        base = eng.generate("w" * 30, sp).tokens
        chunks0 = eng.prefill_chunks
        # preempt once at the first decode block (global decode_steps is
        # already past 2) -> swap-out #1; then land a mid-prefill
        # preemption DURING the resume's restore: the 30-token row takes 4
        # initial chunks, so the restore's rounds run from chunks0+5 on
        FAULTS.arm("engine.force_preempt", after_steps=2)
        FAULTS.arm("engine.preempt_mid_prefill", after_steps=chunks0 + 5)
        fut = eng.submit("w" * 30, sp)
        r = fut.result(timeout=180)
        assert r.tokens == base
        assert r.preempt_count == 2
        assert eng.kv_swap_outs == 2  # decode preempt + mid-restore re-put
        tl = eng.flight.timeline(fut.rid)
        outs = [e for e in tl if e["kind"] == "swap_out"]
        ins = [e for e in tl if e["kind"] == "swap_in" and not e["detail"].get("error")]
        assert len(outs) == 2 and ins
        # the re-put preserved the WHOLE entry: the second offload and the
        # final restore cover the first offload's rows, not just the few
        # that landed before the mid-restore preemption
        assert outs[1]["detail"]["tokens"] == outs[0]["detail"]["tokens"]
        assert ins[-1]["detail"]["tokens"] == outs[0]["detail"]["tokens"]
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


# -- fault sites + combined stress -------------------------------------------


def test_host_swap_error_falls_back_to_recompute_identically():
    eng = make_engine(kv_pages=10, host_kv_bytes=1 << 22)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        base = eng.generate("hello world " * 4, sp).tokens
        FAULTS.arm("engine.host_swap_error", times=1)
        FAULTS.arm("engine.force_preempt", after_steps=2)
        r = eng.generate("hello world " * 4, sp)
        assert r.tokens == base
        assert r.preempt_count >= 1
        assert eng.kv_swap_outs == 0  # the swap-out failed; resume recomputed
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_host_swap_slow_stall_is_flight_recorded():
    eng = make_engine(kv_pages=10, host_kv_bytes=1 << 22)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        base = eng.generate("hello world " * 4, sp).tokens
        FAULTS.arm("engine.host_swap_slow", times=1, seconds=0.2)
        FAULTS.arm("engine.force_preempt", after_steps=2)
        fut = eng.submit("hello world " * 4, sp)
        r = fut.result(timeout=180)
        assert r.tokens == base
        tl = eng.flight.timeline(fut.rid)
        swaps = [e for e in tl if e["kind"] in ("swap_out", "swap_in")]
        assert swaps, "no swap events on the preempted request's timeline"
        assert any(e["detail"].get("stall_s", 0) > 0.1 for e in swaps)
    finally:
        eng.stop()


def test_stress_pressure_swap_faults_preempt_invariants_armed():
    """Satellite stress: oversubscribed paged pool + page_pressure + both
    swap faults + force_preempt, invariants armed (make_engine default),
    dedup-eligible prompts. Every output must equal its solo run."""
    # cache off: the drain check below expects every page back in the
    # pool, and live cache entries legitimately pin pages at idle
    eng = make_engine(
        kv_pages=16, host_kv_bytes=1 << 20, prefill_chunk=16,
        prefix_cache_entries=0,
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=10)
        prompts = [PERSONA + t for t in TAILS] + ["z" * 24]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        FAULTS.arm("engine.page_pressure", pages=4)
        FAULTS.arm("engine.host_swap_slow", times=2, seconds=0.05)
        FAULTS.arm("engine.host_swap_error", times=1)
        FAULTS.arm("engine.force_preempt", after_steps=3)
        streams = {p: [] for p in prompts}
        with eng.hold_admission():
            futs = [eng.submit(p, sp, on_tokens=streams[p].extend) for p in prompts]
        results = dict(zip(prompts, (f.result(timeout=300) for f in futs)))
        for p, r in results.items():
            assert r.tokens == solo[p], f"stress diverged for {p!r}"
            assert streams[p] == list(r.tokens), "stream replayed across swap resume"
        FAULTS.reset()
        # pages all recycled once the burst drains (held pages released)
        deadline = time.monotonic() + 5
        while eng._allocator.free_count != eng.num_pages - 1:
            assert time.monotonic() < deadline, "leaked KV pages"
            time.sleep(0.05)
        _settle(eng)
        assert verify_engine(eng) == []
    finally:
        eng.stop()
