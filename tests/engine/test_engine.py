"""Engine integration: continuous batching on the virtual 8-device CPU mesh
with a tiny model. Correctness here = scheduling/caching/sampling invariants
(the model itself is validated against HF in test_llama_model.py)."""

import dataclasses
import threading

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer, EOT
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TOK = ByteTokenizer()
# tiny config large enough for the byte tokenizer's vocab, kv heads
# divisible by tp=2
CFG = dataclasses.replace(
    PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2
)


@pytest.fixture(scope="module")
def engine():
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=128,
        prefill_buckets=(32, 64, 128),
        seed=0,
    )
    eng.start()
    yield eng
    eng.stop()


def test_generate_greedy_deterministic(engine):
    r1 = engine.generate("hello", SamplingParams(temperature=0.0, max_tokens=8))
    r2 = engine.generate("hello", SamplingParams(temperature=0.0, max_tokens=8))
    assert r1.tokens == r2.tokens
    assert r1.finish_reason in ("stop", "length")
    assert len(r1.tokens) <= 8
    assert r1.prompt_tokens == 5
    assert r1.ttft_ms >= 0 and r1.latency_ms >= r1.ttft_ms


def test_concurrent_requests_batch_and_match_solo(engine):
    """Continuous batching must not change results: submit 4 concurrent
    greedy requests; each must equal its solo run."""
    prompts = ["aaa", "bbbb", "ccccc", "d"]
    solo = [
        engine.generate(p, SamplingParams(temperature=0.0, max_tokens=6)).tokens
        for p in prompts
    ]
    futures = [
        engine.submit(p, SamplingParams(temperature=0.0, max_tokens=6))
        for p in prompts
    ]
    batched = [f.result(timeout=120).tokens for f in futures]
    assert batched == solo


def test_more_requests_than_slots(engine):
    """Queue depth > slot count: everything still completes (admission
    backpressure, no head-of-line deadlock)."""
    futures = [
        engine.submit(f"req {i}", SamplingParams(temperature=0.0, max_tokens=4))
        for i in range(10)  # > max_slots=4
    ]
    results = [f.result(timeout=180) for f in futures]
    assert len(results) == 10
    assert all(len(r.tokens) <= 4 for r in results)


def test_max_tokens_respected(engine):
    r = engine.generate("x", SamplingParams(temperature=0.0, max_tokens=3))
    assert len(r.tokens) <= 3


def test_temperature_sampling_varies(engine):
    outs = {
        tuple(
            engine.generate(
                "abc", SamplingParams(temperature=1.5, max_tokens=12)
            ).tokens
        )
        for _ in range(5)
    }
    assert len(outs) > 1  # hot sampling should not be constant


def test_long_prompt_truncated_not_crashing(engine):
    r = engine.generate("z" * 500, SamplingParams(temperature=0.0, max_tokens=4))
    assert r.prompt_tokens < 500


def test_cancel_frees_slot_and_waiting_request(engine):
    """cancel() aborts abandoned requests (client timeout/disconnect): an
    active slot is released at the next decode iteration instead of decoding
    to max_tokens; a still-waiting request is cancelled outright."""
    import time as _time

    # fill every slot with long generations, plus one waiting request
    futs = [
        engine.submit("spin " * 4, SamplingParams(temperature=0.7, max_tokens=10_000))
        for _ in range(engine.max_slots + 1)
    ]
    for f in futs:
        engine.cancel(f)
    deadline = _time.monotonic() + 30
    for f in futs:
        try:
            r = f.result(timeout=max(0.1, deadline - _time.monotonic()))
            assert r.finish_reason == "cancelled"
        except Exception:
            assert f.cancelled()
    # engine is healthy and capacity fully recovered
    r = engine.generate("after", SamplingParams(temperature=0.0, max_tokens=4))
    assert len(r.tokens) >= 1
    assert engine.stats()["active_slots"] == 0


def test_engine_crash_recovery():
    """Failure recovery for the data plane: a crashed engine loop is
    rebuilt (fresh KV/slot state, params kept) by ensure_running(); in-flight
    requests fail fast with errors, later requests succeed — mirroring the
    control plane's error-then-requeue posture."""
    import dataclasses as _dc

    cfg = _dc.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=TOK,
        mesh=jax.sharding.Mesh(jax.devices()[:2], ("tp",)),
        max_slots=2, max_ctx=128, prefill_buckets=(64, 128),
    )
    eng.start()
    try:
        before = eng.generate("hello", SamplingParams(temperature=0.0, max_tokens=6))

        # inject a crash: poison the decode program for one dispatch
        real = eng._jit_decode

        def boom(*a, **k):
            eng._jit_decode = real  # heal after the first failure
            raise RuntimeError("injected decode fault")

        eng._jit_decode = boom
        fut = eng.submit("crash me", SamplingParams(temperature=0.0, max_tokens=6))
        try:
            fut.result(timeout=60)
            raise AssertionError("expected the in-flight request to fail")
        except RuntimeError as e:
            assert "engine crashed" in str(e)
        # the future resolves before the crashed thread finishes its drain;
        # join it before asserting deadness
        if eng._thread is not None:
            eng._thread.join(timeout=30)
        assert eng._crashed and not (eng._thread and eng._thread.is_alive())

        # a deliberately stopped engine must NOT restart...
        # (covered implicitly: ensure_running returns False only via _crashed)
        assert eng.ensure_running() is True
        after = eng.generate("hello", SamplingParams(temperature=0.0, max_tokens=6))
        assert after.tokens == before.tokens  # params survived; results identical
    finally:
        eng.stop()
    # ...and once stopped on purpose, ensure_running stays down
    assert eng.ensure_running() is False


def test_prewarm_compiles_and_leaves_clean_state(engine):
    before = engine.stats().get("prefix_cache")
    engine.prewarm(constrained=True)
    st = engine.stats()
    assert st["active_slots"] == 0 and st["waiting"] == 0
    pc = st.get("prefix_cache")
    if pc is not None:
        # dummies left no trace: entries and counters exactly as before
        assert pc == before
    r = engine.generate("after prewarm", SamplingParams(temperature=0.0, max_tokens=4))
    assert len(r.tokens) >= 1
