"""Prefix KV cache: hits must be bit-identical to cold prefills.

Agent workloads re-send growing conversations with identical system
prompts; the engine snapshots prefix KV at bucket boundaries and, on a hit,
copies it into the slot and runs only the suffix (models/llama.py
prefill_continue)."""

import dataclasses

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

CFG = dataclasses.replace(
    PRESETS["tiny"], vocab_size=512, max_seq_len=512, n_kv_heads=2
)


def _engine(prefix_entries: int) -> Engine:
    eng = Engine(
        config=CFG,
        tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=4,
        max_ctx=256,
        prefill_buckets=(64, 128, 256),
        decode_block_size=4,
        prefix_cache_entries=prefix_entries,
        seed=0,
    )
    eng.start()
    return eng


SYSTEM = "you are an agent with tools. " * 4  # > smallest bucket (64 bytes)


def test_hit_results_match_cold_engine():
    greedy = SamplingParams(temperature=0.0, max_tokens=12)
    cached = _engine(prefix_entries=4)
    cold = _engine(prefix_entries=0)
    try:
        prompts = [SYSTEM + "turn one", SYSTEM + "turn one plus more text"]
        # first generation seeds the cache; the second must hit it
        out_cached = [cached.generate(p, greedy).tokens for p in prompts]
        assert cached.stats()["prefix_cache"]["entries"] >= 1
        hits_before = cached.stats()["prefix_cache"]["hits"]
        out_cached.append(cached.generate(prompts[1], greedy).tokens)
        assert cached.stats()["prefix_cache"]["hits"] > hits_before

        out_cold = [cold.generate(p, greedy).tokens for p in prompts]
        out_cold.append(cold.generate(prompts[1], greedy).tokens)
        assert out_cached == out_cold
    finally:
        cached.stop()
        cold.stop()


def test_growing_conversation_reuses_prefix():
    """Multi-turn shape: each prompt extends the previous one (conversation
    re-sent in full). Later turns must hit and stay correct."""
    greedy = SamplingParams(temperature=0.0, max_tokens=8)
    cached = _engine(prefix_entries=4)
    cold = _engine(prefix_entries=0)
    try:
        convo = SYSTEM
        for turn in range(3):
            convo += f" user says thing {turn}. assistant replies."
            a = cached.generate(convo, greedy).tokens
            b = cold.generate(convo, greedy).tokens
            assert a == b, f"turn {turn} diverged under prefix caching"
        assert cached.stats()["prefix_cache"]["hits"] >= 1
    finally:
        cached.stop()
        cold.stop()


def test_forced_prefix_and_json_through_cache_hit():
    """tool_choice forcing + grammar must survive the hit path (constraint
    state is seeded past the forced prefix regardless of where the KV came
    from)."""
    import json

    prefix = tuple(ByteTokenizer().encode('{"name": "t", "arguments": {'))
    sp = SamplingParams(temperature=1.1, max_tokens=24, json_only=True, forced_prefix=prefix)
    eng = _engine(prefix_entries=4)
    try:
        r1 = eng.generate(SYSTEM + "call it", sp)
        r2 = eng.generate(SYSTEM + "call it", sp)  # hit
        assert eng.stats()["prefix_cache"]["hits"] >= 1
        for r in (r1, r2):
            obj = json.loads(r.text)
            assert obj["name"] == "t"
    finally:
        eng.stop()


def test_concurrent_mixed_hits_and_misses():
    greedy = SamplingParams(temperature=0.0, max_tokens=8)
    eng = _engine(prefix_entries=4)
    try:
        eng.generate(SYSTEM + "seed", greedy)  # seeds the SYSTEM prefix
        prompts = [SYSTEM + f"variant {i}" for i in range(3)] + ["totally different"]
        solo = [eng.generate(p, greedy).tokens for p in prompts]
        futs = [eng.submit(p, greedy) for p in prompts]
        burst = [f.result(timeout=300).tokens for f in futs]
        assert burst == solo
    finally:
        eng.stop()


def test_chunked_prefill_long_prompt():
    """Prompts longer than the largest prefill bucket run as several
    bounded continuation dispatches; greedy results must equal an engine
    whose buckets cover the prompt in one shot."""
    greedy = SamplingParams(temperature=0.0, max_tokens=8)
    small_buckets = Engine(
        config=CFG, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=512, prefill_buckets=(64,),  # force chunking
        decode_block_size=4, prefix_cache_entries=0, seed=0,
    )
    big_buckets = Engine(
        config=CFG, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=512, prefill_buckets=(64, 256),
        decode_block_size=4, prefix_cache_entries=0, seed=0,
    )
    small_buckets.start()
    big_buckets.start()
    try:
        prompt = "a long conversation transcript. " * 7  # ~220 tokens
        a = small_buckets.generate(prompt, greedy).tokens
        b = big_buckets.generate(prompt, greedy).tokens
        assert a == b
        # and chunking composes with the prefix cache
        cached = Engine(
            config=CFG, tokenizer=ByteTokenizer(),
            mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
            max_slots=2, max_ctx=512, prefill_buckets=(64,),
            decode_block_size=4, prefix_cache_entries=4, seed=0,
        )
        cached.start()
        try:
            c1 = cached.generate(prompt, greedy).tokens
            c2 = cached.generate(prompt + " more", greedy).tokens
            assert c1 == a
            assert cached.stats()["prefix_cache"]["hits"] >= 1
            assert c2 == big_buckets.generate(prompt + " more", greedy).tokens
        finally:
            cached.stop()
    finally:
        small_buckets.stop()
        big_buckets.stop()


def _paged_engine(prefix_entries: int) -> Engine:
    eng = Engine(
        config=CFG,
        tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=4,
        max_ctx=256,
        prefill_buckets=(64, 128, 256),
        decode_block_size=4,
        kv_layout="paged",
        page_size=16,
        prefix_cache_entries=prefix_entries,
        seed=0,
    )
    eng.start()
    return eng


def test_paged_hit_results_match_cold_engine():
    """Paged layout shares prefix PAGES zero-copy (refcounted block-table
    references); hits must still be bit-identical to cold prefills."""
    greedy = SamplingParams(temperature=0.0, max_tokens=10)
    cached = _paged_engine(prefix_entries=4)
    cold = _paged_engine(prefix_entries=0)
    try:
        prompts = [SYSTEM + "turn one", SYSTEM + "turn one and then some"]
        out_cached = [cached.generate(p, greedy).tokens for p in prompts]
        assert cached.stats()["prefix_cache"]["entries"] >= 1
        assert cached.stats()["prefix_cache"]["hits"] >= 1  # prompt 2 reused prompt 1's pages
        out_cold = [cold.generate(p, greedy).tokens for p in prompts]
        assert out_cached == out_cold
    finally:
        cached.stop()
        cold.stop()


def test_paged_prefix_page_refcounts_conserved():
    """Page accounting: after all requests drain, the only pages still out
    are exactly the cached entries' shared pages; disabling the cache (0
    entries) returns the pool to full."""
    greedy = SamplingParams(temperature=0.0, max_tokens=6)
    eng = _paged_engine(prefix_entries=2)
    initial_free = eng._allocator.free_count
    try:
        for i in range(5):  # several prompts; entries capped at 2 (LRU evicts)
            eng.generate(SYSTEM + f"variant {i}", greedy)
        import time as _time

        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline and eng.stats()["active_slots"]:
            _time.sleep(0.05)
        held = sum(
            len(e["pages"]) for e in eng._prefix_cache.values() if "pages" in e
        )
        assert held > 0
        assert eng._allocator.free_count == initial_free - held
        # evict everything (simulate) and the pool must be whole again
        with eng._prefix_lock:
            while eng._prefix_cache:
                _, old = eng._prefix_cache.popitem(last=False)
                eng._allocator.free(old["pages"])
        assert eng._allocator.free_count == initial_free
    finally:
        eng.stop()


def test_paged_entry_eviction_while_borrower_active_is_safe():
    """An entry evicted while a sequence still references its pages must not
    free them out from under the borrower (refcounts): the borrower's
    output is unaffected and pages return only when it finishes."""
    eng = _paged_engine(prefix_entries=1)  # capacity 1: next save evicts
    cold = _paged_engine(prefix_entries=0)
    try:
        seed_prompt = SYSTEM + "base"
        eng.generate(seed_prompt, SamplingParams(temperature=0.0, max_tokens=4))
        # borrower: long generation that HITS the entry and keeps running
        borrower = eng.submit(
            seed_prompt + " extended turn", SamplingParams(temperature=0.0, max_tokens=48)
        )
        # a different prompt's save evicts the (capacity-1) entry mid-flight
        eng.generate("completely different " * 10, SamplingParams(temperature=0.0, max_tokens=4))
        got = borrower.result(timeout=120).tokens
        want = cold.generate(
            seed_prompt + " extended turn", SamplingParams(temperature=0.0, max_tokens=48)
        ).tokens
        assert got == want
    finally:
        eng.stop()
        cold.stop()


def test_paged_chunked_prefill_long_prompt():
    """Paged layout no longer requires buckets to reach max_ctx: long
    prompts spill through the paged continuation program; greedy equality
    vs a single-shot paged engine, and composes with the paged prefix
    cache."""
    greedy = SamplingParams(temperature=0.0, max_tokens=8)

    def paged(buckets, entries):
        e = Engine(
            config=CFG, tokenizer=ByteTokenizer(),
            mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
            max_slots=2, max_ctx=512, prefill_buckets=buckets,
            decode_block_size=4, kv_layout="paged", page_size=16,
            prefix_cache_entries=entries, seed=0,
        )
        e.start()
        return e

    small = paged((64,), 0)  # forces chunking
    big = paged((64, 512), 0)
    try:
        prompt = "a long paged conversation transcript. " * 7  # ~260 tokens
        a = small.generate(prompt, greedy).tokens
        b = big.generate(prompt, greedy).tokens
        assert a == b
        cached = paged((64,), 4)
        try:
            c1 = cached.generate(prompt, greedy).tokens
            c2 = cached.generate(prompt + " more", greedy).tokens
            assert c1 == a
            assert cached.stats()["prefix_cache"]["hits"] >= 1
            assert c2 == big.generate(prompt + " more", greedy).tokens
        finally:
            cached.stop()
    finally:
        small.stop()
        big.stop()
