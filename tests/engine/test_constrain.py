"""Grammar-constrained decoding: automaton correctness + engine guarantee
that constrained generations always parse as JSON."""

import dataclasses
import json

import numpy as np
import pytest

import jax

from agentcontrolplane_tpu.engine.constrain import (
    JsonByteAutomaton,
    build_token_table,
)
from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TOK = ByteTokenizer()


def run_text(auto, text):
    return auto.run_bytes(auto.start, text.encode())


def test_automaton_accepts_valid_json():
    auto = JsonByteAutomaton()
    for text in [
        '{"name": "web__fetch", "arguments": {"url": "https://x.com"}}',
        '{"a": [1, 2.5, -3e2], "b": true, "c": null, "d": {}}',
        '{ "k" : "v with spaces and \\" escape" }',
        "{}",
        '{"nested": {"deep": {"deeper": [{"x": 1}]}}}',
    ]:
        sid = run_text(auto, text)
        assert sid >= 0 and auto.is_done(sid), text


def test_automaton_rejects_invalid_json():
    auto = JsonByteAutomaton()
    for text in [
        "not json",
        '{"unterminated": "string',
        '{"a": 1,,}',
        '{"a": 1}}',  # extra closer
        '[1, 2]',  # top level must be an object
        '{a: 1}',  # unquoted key
        '{"a" 1}',  # missing colon
    ]:
        sid = run_text(auto, text)
        assert sid < 0 or not auto.is_done(sid), text


def test_automaton_depth_cap():
    auto = JsonByteAutomaton(max_depth=3)
    assert run_text(auto, '{"a": {"b": 1}}') >= 0
    assert run_text(auto, '{"a": {"b": {"c": {"d": 1}}}}') < 0


def test_token_table_byte_tokenizer():
    table = build_token_table(TOK)
    t = table.token_trans
    # at start: only '{' leads anywhere
    start_allowed = {b for b in range(256) if t[table.start_state, b] >= 0}
    assert start_allowed == {ord("{")}
    # specials are forbidden mid-grammar
    assert t[table.start_state, 256:].max() < 0


def test_engine_json_only_always_parses():
    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(), mesh=mesh,
        max_slots=2, max_ctx=128, prefill_buckets=(32, 64, 128),
    )
    eng.start()
    try:
        # a RANDOM model under hot sampling — without the grammar this is
        # line noise; with it, every completed output must parse
        for i in range(4):
            r = eng.generate(
                f"tool call {i}:",
                SamplingParams(temperature=1.2, max_tokens=120, json_only=True),
            )
            if r.finish_reason == "length":
                continue  # ran out of budget mid-object: structural prefix only
            obj = json.loads(r.text)
            assert isinstance(obj, dict)
        # unconstrained requests on the same engine still work
        r = eng.generate("plain", SamplingParams(temperature=0.0, max_tokens=5))
        assert r.finish_reason in ("stop", "length")
    finally:
        eng.stop()


def test_automaton_random_walks_always_parse():
    """Any random legal walk through the byte automaton that reaches DONE
    must json.loads — the guarantee is total (exact literals, full number
    grammar, strict escapes, no trailing commas), not merely structural."""
    import random

    import numpy as np

    from agentcontrolplane_tpu.engine.constrain import JsonByteAutomaton

    auto = JsonByteAutomaton()
    trans = np.stack(auto._trans)
    rng = random.Random(1234)
    completed = 0
    for _ in range(500):
        sid = auto.start
        out = bytearray()
        for _ in range(200):
            legal = np.nonzero(trans[sid] >= 0)[0]
            assert len(legal) > 0, f"dead end after {bytes(out)!r}"
            b = int(rng.choice(legal))
            out.append(b)
            sid = int(trans[sid][b])
            if auto.is_done(sid):
                break
        if auto.is_done(sid):
            obj = json.loads(out.decode("utf-8", "replace"))
            assert isinstance(obj, dict)
            completed += 1
    assert completed > 100  # the walks genuinely exercise completion


def test_forced_prefix_tool_call_always_parses():
    """tool_choice forcing: teacher-force the '{"name": "X", "arguments": {'
    envelope, grammar-constrain the rest — a RANDOM model's completion must
    ALWAYS be a parseable call to X (engine/client.py tool_choice)."""
    from agentcontrolplane_tpu.engine.toolparse import parse_tool_calls

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(), mesh=mesh,
        max_slots=2, max_ctx=160, prefill_buckets=(64, 128),
    )
    eng.start()
    try:
        prefix = tuple(ByteTokenizer().encode('{"name": "web__fetch", "arguments": {'))
        for i in range(3):
            r = eng.generate(
                f"call the tool {i}",
                SamplingParams(
                    temperature=1.3, max_tokens=100, json_only=True,
                    forced_prefix=prefix,
                ),
            )
            if r.finish_reason == "length":
                continue
            calls = parse_tool_calls(r.text)
            assert len(calls) == 1, r.text
            assert calls[0].function.name == "web__fetch"
            json.loads(calls[0].function.arguments)
        # illegal prefix fails fast instead of generating garbage
        bad = tuple(ByteTokenizer().encode('}{ not json'))
        fut = eng.submit("x", SamplingParams(json_only=True, forced_prefix=bad))
        try:
            fut.result(timeout=30)
            raise AssertionError("expected illegal-prefix failure")
        except RuntimeError as e:
            assert "forced_prefix" in str(e)
    finally:
        eng.stop()


def test_budget_aware_constraint_always_completes():
    """json_only + tight max_tokens: the budget-aware mask steers generation
    to close the object IN BUDGET — output always json.loads, even when the
    finish_reason is 'length'."""
    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(), mesh=mesh,
        max_slots=2, max_ctx=128, prefill_buckets=(32, 64), decode_block_size=4,
    )
    eng.start()
    try:
        for max_toks in (8, 12, 24):
            for i in range(2):
                r = eng.generate(
                    f"go {i}",
                    SamplingParams(temperature=1.3, max_tokens=max_toks, json_only=True),
                )
                obj = json.loads(r.text)
                assert isinstance(obj, dict), r.text
        # forced tool envelope under a budget must still close
        prefix = tuple(ByteTokenizer().encode('{"name": "t", "arguments": {'))
        for i in range(3):
            r = eng.generate(
                f"x{i}",
                SamplingParams(
                    temperature=1.3, max_tokens=16, json_only=True,
                    forced_prefix=prefix,
                ),
            )
            obj = json.loads(r.text)
            assert obj["name"] == "t" and isinstance(obj["arguments"], dict), r.text
    finally:
        eng.stop()
