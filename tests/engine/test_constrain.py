"""Grammar-constrained decoding: automaton correctness + engine guarantee
that constrained generations always parse as JSON."""

import dataclasses
import json

import numpy as np
import pytest

import jax

from agentcontrolplane_tpu.engine.constrain import (
    JsonByteAutomaton,
    build_token_table,
)
from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TOK = ByteTokenizer()


def run_text(auto, text):
    return auto.run_bytes(auto.start, text.encode())


def test_automaton_accepts_valid_json():
    auto = JsonByteAutomaton()
    for text in [
        '{"name": "web__fetch", "arguments": {"url": "https://x.com"}}',
        '{"a": [1, 2.5, -3e2], "b": true, "c": null, "d": {}}',
        '{ "k" : "v with spaces and \\" escape" }',
        "{}",
        '{"nested": {"deep": {"deeper": [{"x": 1}]}}}',
    ]:
        sid = run_text(auto, text)
        assert sid >= 0 and auto.is_done(sid), text


def test_automaton_rejects_invalid_json():
    auto = JsonByteAutomaton()
    for text in [
        "not json",
        '{"unterminated": "string',
        '{"a": 1,,}',
        '{"a": 1}}',  # extra closer
        '[1, 2]',  # top level must be an object
        '{a: 1}',  # unquoted key
        '{"a" 1}',  # missing colon
    ]:
        sid = run_text(auto, text)
        assert sid < 0 or not auto.is_done(sid), text


def test_automaton_depth_cap():
    auto = JsonByteAutomaton(max_depth=3)
    assert run_text(auto, '{"a": {"b": 1}}') >= 0
    assert run_text(auto, '{"a": {"b": {"c": {"d": 1}}}}') < 0


def test_token_table_byte_tokenizer():
    table = build_token_table(TOK)
    t = table.token_trans
    # at start: only '{' leads anywhere
    start_allowed = {b for b in range(256) if t[table.start_state, b] >= 0}
    assert start_allowed == {ord("{")}
    # specials are forbidden mid-grammar
    assert t[table.start_state, 256:].max() < 0


def test_engine_json_only_always_parses():
    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(), mesh=mesh,
        max_slots=2, max_ctx=128, prefill_buckets=(32, 64, 128),
    )
    eng.start()
    try:
        # a RANDOM model under hot sampling — without the grammar this is
        # line noise; with it, every completed output must parse
        for i in range(4):
            r = eng.generate(
                f"tool call {i}:",
                SamplingParams(temperature=1.2, max_tokens=120, json_only=True),
            )
            if r.finish_reason == "length":
                continue  # ran out of budget mid-object: structural prefix only
            obj = json.loads(r.text)
            assert isinstance(obj, dict)
        # unconstrained requests on the same engine still work
        r = eng.generate("plain", SamplingParams(temperature=0.0, max_tokens=5))
        assert r.finish_reason in ("stop", "length")
    finally:
        eng.stop()
