"""Synthetic full-shape checkpoints (engine/weights.write_synthetic_checkpoint).

The no-egress environment can never download real Llama-3 weights, so the
load -> quantize -> shard -> serve path is exercised with generated
checkpoints that are byte-format-identical to real ones (HF tensor names,
bf16, multi-shard). CPU scale here; the full 16 GiB 8B run is the
hardware-gated test below (ACP_TEST_TPU=1).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from agentcontrolplane_tpu.engine.weights import (
    load_safetensors_dir,
    write_synthetic_checkpoint,
)
from agentcontrolplane_tpu.models.llama import PRESETS, LlamaConfig, forward

# small but structurally honest: GQA (kv < heads), untied lm_head,
# multi-shard at the chosen shard size
SMALL = LlamaConfig(
    vocab_size=512, dim=128, n_layers=3, n_heads=4, n_kv_heads=2,
    ffn_dim=256, rope_theta=10000.0, max_seq_len=256, tie_embeddings=False,
)


def test_synthetic_checkpoint_round_trips(tmp_path):
    import json

    path = str(tmp_path / "synth")
    total = write_synthetic_checkpoint(path, SMALL, max_shard_bytes=200_000)
    shards = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    assert len(shards) > 1, "must exercise the multi-shard load path"
    # real HF wire format: -of- shard names + index with a full weight map
    assert all(f"-of-{len(shards):05d}" in f for f in shards)
    assert os.path.exists(os.path.join(path, "config.json"))
    with open(os.path.join(path, "model.safetensors.index.json")) as f:
        index = json.load(f)
    assert index["metadata"]["total_size"] == total
    assert set(index["weight_map"].values()) == set(shards)
    on_disk = sum(
        os.path.getsize(os.path.join(path, f)) for f in shards
    )
    assert on_disk >= total  # tensor bytes + safetensors headers

    params, config = load_safetensors_dir(path)
    assert config.dim == SMALL.dim and config.n_kv_heads == 2
    logits = np.asarray(forward(params, jnp.ones((1, 8), dtype=jnp.int32), config))
    assert np.all(np.isfinite(logits))


def test_synthetic_checkpoint_refuses_variant_architectures(tmp_path):
    import dataclasses as dc

    for variant in (
        dc.replace(SMALL, qkv_bias=True),
        dc.replace(SMALL, n_experts=4),
        dc.replace(SMALL, head_dim_override=64),
        dc.replace(SMALL, hidden_act="gelu_tanh"),
        dc.replace(SMALL, embed_scale=True),
    ):
        with pytest.raises(ValueError, match="plain Llama"):
            write_synthetic_checkpoint(str(tmp_path / "x"), variant)


def test_rope_scaling_round_trips_through_checkpoint(tmp_path):
    """A llama3.1-style config (rope_scaling in config.json) must survive
    generate -> load with the scaling intact, and non-llama3 scaling types
    must be refused at load rather than silently mis-served."""
    import dataclasses as dc
    import json

    scaled = dc.replace(
        SMALL, rope_scaling_factor=8.0, rope_original_max_seq=64
    )
    path = str(tmp_path / "synth31")
    write_synthetic_checkpoint(path, scaled)
    _, config = load_safetensors_dir(path)
    assert config.rope_scaling_factor == 8.0
    assert config.rope_original_max_seq == 64

    # YaRN / linear scaling types: refuse, don't serve the wrong function
    cfg = json.load(open(os.path.join(path, "config.json")))
    cfg["rope_scaling"] = {"rope_type": "yarn", "factor": 4.0}
    json.dump(cfg, open(os.path.join(path, "config.json"), "w"))
    with pytest.raises(ValueError, match="rope_scaling"):
        load_safetensors_dir(path)


def test_refuses_to_overwrite_unmarked_checkpoint(tmp_path):
    """A dir holding shards NOT written by this generator (i.e. possibly a
    real downloaded checkpoint) must never be cleared — that would be
    irreversible data loss in a no-egress environment."""
    import json

    path = tmp_path / "real"
    path.mkdir()
    (path / "model-00001-of-00002.safetensors").write_bytes(b"precious")
    (path / "config.json").write_text(json.dumps({"model_type": "llama"}))
    with pytest.raises(ValueError, match="refusing to overwrite"):
        write_synthetic_checkpoint(str(path), SMALL)
    assert (path / "model-00001-of-00002.safetensors").read_bytes() == b"precious"


def test_rerun_does_not_mix_generations(tmp_path):
    """The loader reads every *.safetensors in the dir, so a rerun with a
    different shard size must fully replace the previous generation."""
    import dataclasses as dc
    import json

    path = str(tmp_path / "synth")
    write_synthetic_checkpoint(path, SMALL, max_shard_bytes=100_000)
    many = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    # rerun: one big shard AND a smaller config — stale shards must be gone
    smaller = dc.replace(SMALL, n_layers=2)
    write_synthetic_checkpoint(path, smaller, max_shard_bytes=1 << 30)
    now = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    assert len(now) == 1 and len(many) > 1
    with open(os.path.join(path, "model.safetensors.index.json")) as f:
        assert set(json.load(f)["weight_map"].values()) == set(now)
    params, config = load_safetensors_dir(path)
    assert config.n_layers == 2


def test_synthetic_checkpoint_serves_through_engine(tmp_path):
    """The whole CLI path minus argv: load (+int8 quantize) -> Engine ->
    first token, exactly what `acp-tpu run --tpu-checkpoint X
    --tpu-quantize int8` does."""
    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.ops.quant import QuantizedTensor
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    path = str(tmp_path / "synth")
    write_synthetic_checkpoint(path, SMALL, max_shard_bytes=200_000)
    t0 = time.monotonic()
    params, config = load_safetensors_dir(path, quantize="int8")
    load_s = time.monotonic() - t0
    assert isinstance(params["layers"]["wq"], QuantizedTensor)

    engine = Engine(
        config=config, params=params, tokenizer=ByteTokenizer(),
        # tp=2: the synthetic config's 2 KV heads must divide the mesh
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=64, prefill_buckets=(32, 64),
        decode_block_size=4, seed=0,
    )
    engine.start()
    try:
        result = engine.generate("hello", SamplingParams(temperature=0.0, max_tokens=4))
        assert len(result.tokens) > 0
    finally:
        engine.stop()
    assert load_s < 60


@pytest.mark.skipif(
    not os.environ.get("ACP_TEST_TPU"),
    reason="set ACP_TEST_TPU=1 to run the full-size 8B leg on the real TPU",
)
def test_full_size_8b_synthetic_checkpoint_on_tpu():
    """VERDICT r4 #7: generate a REAL-SIZE llama3-8b-shaped checkpoint
    (~16 GiB), serve it int8-quantized on the chip, record load time and
    first token. Cached under /tmp/tpu_runs so reruns skip the ~16 GiB
    write."""
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer

    path = "/tmp/tpu_runs/synth8b"
    cfg = PRESETS["llama3-8b"]
    if not os.path.exists(os.path.join(path, "config.json")):
        t0 = time.monotonic()
        total = write_synthetic_checkpoint(path, cfg)
        print(f"synth 8B: wrote {total / 1e9:.1f} GB in {time.monotonic() - t0:.0f}s")

    t0 = time.monotonic()
    params, config = load_safetensors_dir(path, quantize="int8")
    load_s = time.monotonic() - t0

    # single chip: int8 8B (~8 GiB weights) fits a 16 GiB v5e
    engine = Engine(
        config=dataclasses.replace(config, max_seq_len=512),
        params=params, tokenizer=ByteTokenizer(), quantize="int8",
        max_slots=8, max_ctx=512, prefill_buckets=(128, 512),
        decode_block_size=16, seed=0,
    )
    engine.start()
    try:
        t0 = time.monotonic()
        result = engine.generate("hello", SamplingParams(temperature=0.0, max_tokens=8))
        first_gen_s = time.monotonic() - t0
        assert len(result.tokens) > 0
        print(
            f"synth 8B on TPU: load+quantize {load_s:.1f}s, "
            f"first generate (incl. compile) {first_gen_s:.1f}s"
        )
    finally:
        engine.stop()
