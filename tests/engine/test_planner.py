"""Admission-time chunk-rate planner + scheduler autopilot (ISSUE 13):

- quota arithmetic (engine/planner.py project_quota): tokens-remaining /
  cycles-until-deadline, clamped sane at every edge;
- the engine integration: a tight-deadline long prompt gets a quota-sized
  per-cycle chunk and FINISHES where the flat one-chunk cadence would
  expire mid-prefill — deadlines met by arithmetic, not EDF luck;
- reprojection: preempt→resume re-enters admission and re-plans (flight
  ``quota`` events carry reason=resume; the counter rises);
- quota-vs-actual surfaces in the request timeline (``rate_plan`` block);
- the autopilot's recommend() policy: each bounded step moves the right
  knob in the right direction, never past its limits.
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.planner import (
    Autopilot,
    AutopilotLimits,
    CycleClock,
    project_quota,
    recommend,
)
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    kw.setdefault("prefix_cache_entries", 0)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=256,
        prefill_buckets=(32, 64, 128, 256),
        width_buckets=(1, 2, 4),
        decode_block_size=4,
        kv_layout="paged",
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str, **labels) -> float:
    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    return m.values.get(tuple(sorted(labels.items())), 0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- quota arithmetic ---------------------------------------------------------


def test_project_quota_arithmetic():
    # 200 tokens, 16-token chunks = 13 chunks; 0.5s left at 50ms cycles =
    # 10 cycles - 2 slack = 8 -> ceil(13/8) = 2 chunks per cycle
    assert project_quota(200, 16, 0.5, 0.05) == 2
    # plenty of time: the flat PR 7 cadence
    assert project_quota(200, 16, 60.0, 0.05) == 1
    # desperately tight: capped at max_quota, never unbounded
    assert project_quota(4096, 16, 0.01, 0.05, max_quota=8) == 8
    # edges: no deadline / already expired / nothing left / bad chunk
    assert project_quota(200, 16, None, 0.05) == 1
    assert project_quota(200, 16, -1.0, 0.05) == 1
    assert project_quota(0, 16, 0.5, 0.05) == 1
    assert project_quota(200, 0, 0.5, 0.05) == 1
    # degenerate clock seeds never divide by zero
    assert project_quota(200, 16, 0.5, 0.0) >= 1


def test_cycle_clock_ewma_seeds_and_decays():
    clk = CycleClock(alpha=0.5)
    assert clk.cycle_s == 0.0
    clk.observe(0.1)
    assert clk.cycle_s == pytest.approx(0.1)
    clk.observe(0.3)
    assert clk.cycle_s == pytest.approx(0.2)
    clk.observe(-1.0)  # ignored
    assert clk.cycle_s == pytest.approx(0.2)


# -- deadlines met by arithmetic ----------------------------------------------


def test_planner_meets_deadline_flat_cadence_would_miss():
    """One long prompt, chunk=8, ~20ms per cycle (stalled deterministically),
    deadline 0.45s: the flat cadence needs ~25 cycles (~0.5s+) and expires
    mid-prefill; the planner's quota-sized chunks finish in time. Same
    engine, same stall — only the planner knob differs."""
    prompt = [1 + (i % 250) for i in range(200)]
    sp = SamplingParams(temperature=0.0, max_tokens=4)

    def run(planner: bool):
        eng = make_engine(prefill_chunk=8, rate_planner=planner)
        real = eng._prefill_chunks

        def slow_chunks(budget):
            time.sleep(0.02)
            return real(budget)

        eng._prefill_chunks = slow_chunks
        # seed the cycle clock so admission projects against the real
        # (stalled) cadence instead of the cold-start default
        eng._cycle_clock.observe(0.02)
        try:
            fut = eng.submit(prompt, sp, timeout_s=0.45)
            try:
                return ("ok", fut.result(timeout=120).tokens)
            except Exception as e:
                return ("expired", type(e).__name__)
        finally:
            eng.stop()

    flat = run(False)
    planned = run(True)
    assert flat[0] == "expired", flat
    assert planned[0] == "ok", planned


def test_quota_projection_event_and_chunk_sizing():
    """Admission records a ``quota`` flight event and the scheduler sizes
    the slot's per-cycle chunk as quota x chunk (capped at the largest
    bucket, page-aligned)."""
    eng = make_engine(prefill_chunk=8)
    try:
        eng._cycle_clock.observe(0.05)
        fut = eng.submit(
            [1 + (i % 250) for i in range(200)],
            SamplingParams(temperature=0.0, max_tokens=4),
            timeout_s=0.6,
        )
        fut.result(timeout=120)
        quotas = [e for e in eng.flight.events(kind="quota")]
        assert quotas, "no quota projection recorded"
        q = quotas[-1]["detail"]
        assert q["reason"] == "admit"
        assert q["quota"] >= 2
        # the chunk sizing followed the quota: at least one chunk bigger
        # than the base grain dispatched
        chunks = [e["detail"]["n"] for e in eng.flight.events(kind="prefill_chunk")]
        assert max(chunks) >= 2 * 8, chunks
    finally:
        eng.stop()


def test_preempt_resume_reprojects_quota():
    """A deadline request preempted mid-prefill re-enters admission and
    REPROJECTS its plan: reason=resume quota event + the reprojection
    counter. Output still completes (resume is byte-identical; pinned
    elsewhere — here the plan bookkeeping is the subject)."""
    eng = make_engine(prefill_chunk=8)
    try:
        eng._cycle_clock.observe(0.01)
        re0 = counter("acp_engine_quota_reprojections_total")
        FAULTS.arm(
            "engine.preempt_mid_prefill", times=1,
            after_steps=eng.prefill_chunks + 2,
        )
        fut = eng.submit(
            [1 + (i % 250) for i in range(200)],
            SamplingParams(temperature=0.0, max_tokens=4),
            timeout_s=30.0,
        )
        fut.result(timeout=180)
        reasons = [
            e["detail"]["reason"] for e in eng.flight.events(kind="quota")
        ]
        assert "resume" in reasons, reasons
        assert counter("acp_engine_quota_reprojections_total") > re0
    finally:
        eng.stop()


def test_timeline_surfaces_rate_plan():
    """The request timeline carries quota-vs-actual (the acp-tpu timeline
    CLI prints this block)."""
    eng = make_engine(prefill_chunk=8)
    try:
        eng._cycle_clock.observe(0.05)
        fut = eng.submit(
            [1 + (i % 250) for i in range(120)],
            SamplingParams(temperature=0.0, max_tokens=4),
            timeout_s=5.0,
        )
        fut.result(timeout=120)
        rid = fut.rid
        doc = eng.flight.timeline_doc(rid)
        assert doc is not None
        rp = doc.get("rate_plan")
        assert rp is not None, "timeline missing the rate_plan block"
        assert rp["quota"] >= 1
        assert rp["chunks_dispatched"] >= 1
        assert rp["chunk_tokens"] >= 120
        assert rp["projections"][0]["reason"] == "admit"
    finally:
        eng.stop()


def test_no_deadline_keeps_flat_cadence():
    """Deadline-free requests keep quota 1 — the planner is inert for them
    (exactly the PR 7 cadence, no quota events beyond the projection)."""
    eng = make_engine(prefill_chunk=8)
    try:
        fut = eng.submit(
            [1 + (i % 250) for i in range(100)],
            SamplingParams(temperature=0.0, max_tokens=4),
        )
        fut.result(timeout=120)
        chunks = [e["detail"]["n"] for e in eng.flight.events(kind="prefill_chunk")]
        assert chunks and max(chunks) <= 8
    finally:
        eng.stop()


# -- autopilot policy ---------------------------------------------------------

LIMITS = AutopilotLimits(chunk_min=8, chunk_max=256, budget_max=2048, spec_len_max=16)
KNOBS = {"prefill_chunk": 32, "token_budget": 128, "spec_len": 4}


def test_autopilot_raises_budget_when_prefill_bound_and_saturated():
    out = recommend(
        {"prefill": 2.0, "queue_wait": 0.1, "decode": 0.5, "preempt_stall": 0.0},
        utilization_avg=0.99, spec_acceptance=0.5, knobs=KNOBS, limits=LIMITS,
    )
    assert out.get("token_budget", 0) > KNOBS["token_budget"]
    assert out["token_budget"] <= LIMITS.budget_max


def test_autopilot_grows_chunk_when_queue_bound():
    out = recommend(
        {"prefill": 0.1, "queue_wait": 2.0, "decode": 0.5, "preempt_stall": 0.0},
        utilization_avg=0.5, spec_acceptance=None, knobs=KNOBS, limits=LIMITS,
    )
    assert out.get("prefill_chunk") == 64


def test_autopilot_shrinks_chunk_under_preempt_thrash():
    out = recommend(
        {"prefill": 0.1, "queue_wait": 0.1, "decode": 0.5, "preempt_stall": 0.4},
        utilization_avg=0.5, spec_acceptance=None, knobs=KNOBS, limits=LIMITS,
    )
    assert out.get("prefill_chunk") == 16


def test_autopilot_steers_spec_len_by_acceptance():
    low = recommend({}, 0.5, 0.1, KNOBS, LIMITS)
    assert low.get("spec_len") == 3
    high = recommend({}, 0.5, 0.9, KNOBS, LIMITS)
    assert high.get("spec_len") == 5
    mid = recommend({}, 0.5, 0.5, KNOBS, LIMITS)
    assert "spec_len" not in mid
    # bounded: never below 1, never past the cap
    floor = recommend({}, 0.5, 0.0, {**KNOBS, "spec_len": 1}, LIMITS)
    assert "spec_len" not in floor
    cap = recommend({}, 0.5, 1.0, {**KNOBS, "spec_len": 16}, LIMITS)
    assert "spec_len" not in cap


def test_autopilot_holds_when_nothing_dominates():
    out = recommend(
        {"prefill": 0.2, "queue_wait": 0.2, "decode": 0.5, "preempt_stall": 0.0},
        utilization_avg=0.5, spec_acceptance=0.5, knobs=KNOBS, limits=LIMITS,
    )
    assert out == {}


def test_autopilot_due_interval_and_adjustment_count():
    ap = Autopilot(LIMITS, interval=4)
    fires = [ap.due() for _ in range(8)]
    assert fires == [False, False, False, True] * 2
    assert ap.step({}, 0.5, 0.1, KNOBS)  # low acceptance -> a change
    assert ap.adjustments == 1
    assert ap.step({}, 0.5, 0.5, {"prefill_chunk": 0, "token_budget": 0, "spec_len": 0}) == {}
    assert ap.adjustments == 1


def test_autopilot_engine_applies_and_flight_records():
    """Engine integration: with the autopilot armed at a tiny interval and
    spec acceptance forced low, the engine applies a spec_len step and
    flight-records it."""
    eng = make_engine(prefill_chunk=8, spec_len=6, autopilot=True,
                      autopilot_interval=2)
    try:
        a0 = counter("acp_engine_autopilot_adjustments_total")
        # force terrible acceptance so the policy must shrink spec_len
        eng.spec_proposed, eng.spec_accepted = 1000, 10
        futs = [
            eng.submit("steer me " * 4, SamplingParams(temperature=0.0, max_tokens=8))
            for _ in range(3)
        ]
        for f in futs:
            f.result(timeout=120)
        deadline = time.monotonic() + 30
        while eng.spec_len == 6 and time.monotonic() < deadline:
            eng.generate("tick", SamplingParams(temperature=0.0, max_tokens=4))
        assert eng.spec_len < 6
        assert counter("acp_engine_autopilot_adjustments_total") > a0
        assert eng.flight.events(kind="autopilot")
    finally:
        eng.stop()
