"""Unit tests for the KV memory-tier primitives in ops/paged.py:
:class:`HostKVPool` (bounded host-RAM tier: LRU within a byte budget,
rid/prefix matching, conservation audit) and the
:class:`PageAllocator` shared-page counter that backs the dedup gauge.
No engine, no jax dispatches — these pin the host-side accounting the
invariant checker audits."""

import numpy as np
import pytest

from agentcontrolplane_tpu.ops.paged import HostKVEntry, HostKVPool, PageAllocator


def entry(rid: str, n_tokens: int, toks=None) -> HostKVEntry:
    shape = (2, n_tokens, 2, 4)  # [L, T, H_kv, d]
    return HostKVEntry(
        rid=rid,
        tokens=tuple(toks if toks is not None else range(n_tokens)),
        k=np.zeros(shape, dtype=np.float32),
        v=np.zeros(shape, dtype=np.float32),
    )


ENTRY_BYTES = entry("x", 8).nbytes  # 2*8*2*4 floats * 2 arrays = 1024


def test_put_get_pop_accounting():
    pool = HostKVPool(10 * ENTRY_BYTES)
    e = entry("r1", 8)
    assert pool.put(e)
    assert pool.used_bytes == e.nbytes and len(pool) == 1
    assert pool.get("r1") is e
    assert pool.get("nope") is None
    used, entries = pool.audit()
    assert used == sum(entries.values()) == e.nbytes
    assert pool.pop("r1") is e
    assert pool.used_bytes == 0 and len(pool) == 0
    assert pool.pop("r1") is None  # idempotent


def test_reput_same_rid_replaces_without_double_count():
    pool = HostKVPool(10 * ENTRY_BYTES)
    pool.put(entry("r1", 8))
    bigger = entry("r1", 16)
    assert pool.put(bigger)
    assert len(pool) == 1
    assert pool.used_bytes == bigger.nbytes


def test_lru_eviction_within_budget():
    pool = HostKVPool(3 * ENTRY_BYTES)
    for rid in ("a", "b", "c"):
        assert pool.put(entry(rid, 8))
    pool.get("a")  # a lookup refreshes recency: "a" is now the hottest
    assert pool.put(entry("d", 8))
    assert pool.get("b") is None  # least-recently-USED evicted, not oldest
    assert pool.get("a") is not None
    assert pool.used_bytes <= pool.max_bytes


def test_match_prefix_refreshes_recency():
    pool = HostKVPool(2 * ENTRY_BYTES)
    pool.put(entry("old", 8, toks=[1] * 8))
    pool.put(entry("new", 8, toks=[2] * 8))
    assert pool.match_prefix([1] * 8 + [3]).rid == "old"  # touches "old"
    pool.put(entry("third", 8, toks=[4] * 8))
    assert pool.get("new") is None  # "new" was the least recently used
    assert pool.get("old") is not None


def test_oversized_entry_refused():
    pool = HostKVPool(ENTRY_BYTES)
    pool.put(entry("small", 8))
    assert not pool.put(entry("huge", 64))
    # the refusal must not have evicted anything to make room
    assert pool.get("small") is not None
    assert pool.used_bytes == ENTRY_BYTES


def test_match_prefix_longest_strict():
    pool = HostKVPool(10 * ENTRY_BYTES)
    pool.put(entry("short", 4, toks=[1, 2, 3, 4]))
    pool.put(entry("long", 8, toks=[1, 2, 3, 4, 5, 6, 7, 8]))
    pool.put(entry("other", 6, toks=[9, 9, 9, 9, 9, 9]))
    row = [1, 2, 3, 4, 5, 6, 7, 8, 10, 11]
    assert pool.match_prefix(row).rid == "long"
    # strict: an entry covering the WHOLE row cannot match (no suffix
    # tokens left to produce logits)
    assert pool.match_prefix([1, 2, 3, 4]) is None
    assert pool.match_prefix([1, 2, 3, 4, 99]).rid == "short"
    assert pool.match_prefix([42]) is None


def test_clear_resets_accounting():
    pool = HostKVPool(10 * ENTRY_BYTES)
    pool.put(entry("a", 8))
    pool.clear()
    assert pool.used_bytes == 0 and len(pool) == 0


# -- PageAllocator.shared_count ----------------------------------------------


def test_shared_count_tracks_refcounts_incrementally():
    alloc = PageAllocator(16)
    pages = alloc.alloc(4)
    assert alloc.shared_count == 0
    alloc.share(pages[:2])  # refcount 2 on two pages
    assert alloc.shared_count == 2
    alloc.share(pages[:1])  # refcount 3: still ONE shared page
    assert alloc.shared_count == 2
    alloc.free(pages[:1])  # 3 -> 2: still shared
    assert alloc.shared_count == 2
    alloc.free(pages[:2])  # page0 2->1, page1 2->1: no longer shared
    assert alloc.shared_count == 0
    alloc.free(pages)  # last refs drop; pool whole again
    assert alloc.free_count == 15
    free_pages, refs = alloc.audit()
    assert len(free_pages) == 15 and refs == {}


def test_shared_count_survives_interleaved_alloc_free():
    alloc = PageAllocator(8)
    a = alloc.alloc(2)
    alloc.share(a)
    b = alloc.alloc(3)
    alloc.free(b)
    assert alloc.shared_count == 2
    alloc.free(a)
    alloc.free(a)
    assert alloc.shared_count == 0
    with pytest.raises(KeyError):  # double-free still loud
        alloc.free(a)
