"""Engine flight recorder: per-request lifecycle timelines, phase-latency
attribution, OTLP child spans, and crash dumps.

Acceptance contracts pinned here (ISSUE 10):

- a request driven through preempt -> resume yields a timeline showing the
  full decision sequence in monotonic order, its phase durations sum to
  ~end-to-end latency, and the same phases appear as OTLP child spans
  under the submitted trace context;
- arming ``engine.invariant_break`` with ``ACP_FLIGHT_DUMP_DIR`` set
  produces a crash dump containing the violating event's recent history;
- ``ACP_FLIGHT=0`` / ``flight.enabled=False`` reduces recording to one
  bool branch (no events).
"""

import dataclasses
import glob
import json
import os
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.flight import (
    FlightRecorder,
    attribute_phases,
)
from agentcontrolplane_tpu.observability.tracing import Tracer
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


class _Trace:
    """SpanContext-shaped carrier without importing the API layer."""

    def __init__(self, trace_id="ab" * 16, span_id="cd" * 8):
        self.trace_id = trace_id
        self.span_id = span_id


def _wait_timeline(eng, rid, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        doc = eng.flight.timeline_doc(rid)
        if doc is not None and any(e["kind"] == "finish" for e in doc["events"]):
            return doc
        time.sleep(0.02)
    raise AssertionError(f"no finished timeline for rid {rid}")


# -- the acceptance path: preempt -> resume ----------------------------------


def test_preempt_resume_timeline_phases_and_spans():
    """Force a preemption mid-decode; the victim's timeline must replay
    submit -> admit -> prefill_done -> preempt -> (re)admit ->
    prefill_done -> finish in monotonic order, its non-overlapping phase
    durations (queue_wait + prefill + preempt_stall + decode) must sum to
    ~its end-to-end latency, and the same phases must land as OTLP child
    spans under the request's trace context."""
    eng = make_engine(kv_pages=10)
    tracer = Tracer(endpoint="")  # in-memory ring only
    eng.flight.tracer = tracer
    trace = _Trace()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcdef"]
        futs = [eng.submit(p, sp, trace=trace) for p in prompts]
        results = [f.result(timeout=120) for f in futs]
        preempted = [f for f, r in zip(futs, results) if r.preempt_count > 0]
        assert preempted, "tiny pool must have preempted at least one request"
        fut = preempted[0]
        doc = _wait_timeline(eng, fut.rid)

        kinds = [e["kind"] for e in doc["events"]]
        assert kinds[0] == "submit"
        assert "preempt" in kinds
        assert kinds[-1] == "finish"
        # the full decision sequence: admitted, prefilled, preempted,
        # re-admitted (resume), re-prefilled, finished
        assert kinds.count("admit") >= 2
        assert kinds.count("prefill_done") >= 2
        assert kinds.index("admit") < kinds.index("preempt")
        # monotonic ordering, both in seq and stamps
        seqs = [e["seq"] for e in doc["events"]]
        stamps = [e["t"] for e in doc["events"]]
        assert seqs == sorted(seqs) and stamps == sorted(stamps)
        # resume admission is marked as such
        resumes = [
            e for e in doc["events"]
            if e["kind"] == "admit" and e["detail"].get("resumed")
        ]
        assert resumes, "the re-admission must be tagged resumed=True"

        phases = doc["phases"]
        assert phases.get("preempt_stall", 0.0) > 0.0
        total = doc["total_s"]
        summed = sum(v for k, v in phases.items() if k != "tool_overlap_hidden")
        assert summed == pytest.approx(total, rel=0.05, abs=0.05)

        spans = tracer.spans_for_trace(trace.trace_id)
        rid_spans = [
            s for s in spans if s.attributes.get("request_id") == fut.rid
        ]
        got = {s.name for s in rid_spans}
        assert {"engine.queue_wait", "engine.prefill", "engine.decode",
                "engine.preempt_stall"} <= got
        for s in rid_spans:
            assert s.parent_span_id == trace.span_id
            assert s.end_time >= s.start_time
    finally:
        eng.stop()


def test_plain_request_phases_sum_and_decode_blocks_recorded():
    eng = make_engine(kv_layout="slot")
    try:
        fut = eng.submit("hello flight", SamplingParams(temperature=0.0, max_tokens=8))
        fut.result(timeout=60)
        doc = _wait_timeline(eng, fut.rid)
        phases = doc["phases"]
        assert set(phases) >= {"queue_wait", "prefill", "decode"}
        summed = sum(v for k, v in phases.items() if k != "tool_overlap_hidden")
        assert summed == pytest.approx(doc["total_s"], rel=0.05, abs=0.05)
        # batch-level cadence events land in the window (not per-request)
        assert eng.flight.events(kind="decode_block")
    finally:
        eng.stop()


def test_park_adopt_timeline_and_window_filters():
    """A parked turn and its adopting follow-up: the first request's
    timeline ends in park + finish; the second's admission is tagged
    adopted and the window exposes both events filterably."""
    eng = make_engine(kv_layout="paged", park_max_s=30.0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        first = eng.submit("conversation-prefix-" + "x" * 20, sp, park=True)
        first.result(timeout=60)
        doc1 = _wait_timeline(eng, first.rid)
        kinds1 = [e["kind"] for e in doc1["events"]]
        assert "park" in kinds1
        # follow-up turn extends the parked prompt -> adoption
        second = eng.submit(
            "conversation-prefix-" + "x" * 20 + "more turn text", sp
        )
        second.result(timeout=60)
        doc2 = _wait_timeline(eng, second.rid)
        admit2 = [e for e in doc2["events"] if e["kind"] == "admit"]
        adopted = any(e["detail"].get("adopted") for e in admit2)
        adopt_events = eng.flight.events(kind="adopt")
        assert adopted and adopt_events
        assert all(e["kind"] == "adopt" for e in adopt_events)
        # rid filter returns only that request's events
        only = eng.flight.events(rid=second.rid, last=0)
        assert only and all(e.get("rid") == second.rid for e in only)
    finally:
        eng.stop()


def test_shed_and_deadline_expiry_recorded():
    eng = make_engine(kv_layout="slot", max_queue=1)
    try:
        with eng.hold_admission():
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            futs = [eng.submit("p" * 8, sp, timeout_s=60) for _ in range(4)]
            shed = [f for f in futs if f.done() and f.exception() is not None]
            assert shed
            tl = eng.flight.timeline(shed[0].rid)
            assert [e["kind"] for e in tl] == ["submit", "shed"]
            # a queued request whose deadline passes fails fast and records
            # (cap lifted so this one queues instead of shedding)
            eng.max_queue = 0
            doomed = eng.submit("q" * 8, SamplingParams(max_tokens=4), timeout_s=0.01)
            time.sleep(0.05)
        with pytest.raises(Exception):
            doomed.result(timeout=30)
        deadline = time.monotonic() + 10
        tl = None
        while time.monotonic() < deadline:
            tl = eng.flight.timeline(doomed.rid)
            if tl and tl[-1]["kind"] == "expire":
                break
            time.sleep(0.02)
        assert tl and tl[-1]["kind"] == "expire"
        assert tl[-1]["detail"]["where"] == "queued"
        for f in futs:
            if not f.done():
                f.result(timeout=60)
    finally:
        eng.stop()


# -- crash dumps -------------------------------------------------------------


def test_invariant_break_produces_crash_dump_end_to_end(tmp_path, monkeypatch):
    """faults.py's engine.invariant_break proves the dump path: the armed
    checker trips, the crash handler writes the dump BEFORE failing
    futures, and the dump holds the violating event's recent history +
    engine stats + allocator audit; ensure_running then recovers."""
    monkeypatch.setenv("ACP_FLIGHT_DUMP_DIR", str(tmp_path))
    eng = make_engine(kv_layout="paged", check_invariants=True)
    try:
        eng.generate("warmup", SamplingParams(temperature=0.0, max_tokens=4))
        FAULTS.arm("engine.invariant_break")
        fut = eng.submit("boom", SamplingParams(temperature=0.0, max_tokens=8))
        with pytest.raises(Exception, match="crash|invariant"):
            fut.result(timeout=60)
        dumps = sorted(glob.glob(str(tmp_path / "flightdump-*.json")))
        assert dumps, "crash must write a flight dump when the dir is set"
        doc = json.loads(open(dumps[-1]).read())
        assert doc["error"]["type"] == "InvariantViolation"
        kinds = [e["kind"] for e in doc["events"]]
        assert "invariant_violation" in kinds
        # the violating event sits inline with the request's history
        assert "submit" in kinds and "admit" in kinds
        assert "crash" in kinds
        assert doc["engine_stats"]["max_slots"] == 4
        audit = doc["allocator_audit"]
        assert "free" in audit and "refcounts" in audit
        # recovery: the engine serves again after ensure_running
        assert eng.ensure_running()
        r = eng.generate("after", SamplingParams(temperature=0.0, max_tokens=4))
        assert r.tokens
        assert any(e["kind"] == "restart" for e in eng.flight.events(kind="restart"))
    finally:
        eng.stop()


def test_no_dump_dir_means_no_dump(tmp_path, monkeypatch):
    monkeypatch.delenv("ACP_FLIGHT_DUMP_DIR", raising=False)
    monkeypatch.chdir(tmp_path)
    eng = make_engine(kv_layout="slot", check_invariants=True)
    try:
        FAULTS.arm("engine.crash")
        fut = eng.submit("x" * 8, SamplingParams(temperature=0.0, max_tokens=4))
        with pytest.raises(Exception):
            fut.result(timeout=60)
        assert not glob.glob(str(tmp_path / "flightdump-*.json"))
    finally:
        eng.stop()


# -- recorder unit behavior --------------------------------------------------


def test_disabled_recorder_records_nothing():
    rec = FlightRecorder(enabled=False)
    rec.record("submit", rid="r1")
    assert rec.finish("r1", "stop") == {}
    assert rec.events() == []
    assert rec.timeline("r1") is None
    assert rec.stats()["recorded_total"] == 0


def test_env_knob_disables(monkeypatch):
    monkeypatch.setenv("ACP_FLIGHT", "0")
    assert FlightRecorder().enabled is False
    monkeypatch.setenv("ACP_FLIGHT", "1")
    assert FlightRecorder().enabled is True


def test_window_capacity_and_finished_lru():
    rec = FlightRecorder(capacity=16, finished_timelines=2)
    for i in range(100):
        rec.record("decode_block", width=i)
    assert rec.stats()["window_events"] == 16
    assert rec.stats()["recorded_total"] == 100
    for rid in ("a", "b", "c"):
        rec.record("submit", rid=rid)
        rec.finish(rid, "stop")
    assert rec.timeline("a") is None  # evicted from the finished LRU
    assert rec.timeline("b") is not None and rec.timeline("c") is not None
    assert rec.request_ids()[-2:] == ["b", "c"]


def test_per_request_cap_bounds_timeline():
    rec = FlightRecorder(capacity=4096, per_request_cap=8)
    for _ in range(50):
        rec.record("prefill_chunk", rid="big")
    assert len(rec.timeline("big")) == 8


def test_attribute_phases_tool_overlap_and_partial_histories():
    evs = [
        {"seq": 1, "t": 0.0, "kind": "submit"},
        {"seq": 2, "t": 1.0, "kind": "admit"},
        {"seq": 3, "t": 3.0, "kind": "prefill_done"},
        {"seq": 4, "t": 4.0, "kind": "tool_call"},
        {"seq": 5, "t": 9.0, "kind": "finish"},
    ]
    durations, windows = attribute_phases(evs)
    assert durations["queue_wait"] == pytest.approx(1.0)
    assert durations["prefill"] == pytest.approx(2.0)
    assert durations["decode"] == pytest.approx(6.0)
    assert durations["tool_overlap_hidden"] == pytest.approx(5.0)
    assert ("tool_overlap_hidden", 4.0, 9.0) in windows
    # partial: shed before admission -> no phases beyond the events
    durations, _ = attribute_phases(
        [{"seq": 1, "t": 0.0, "kind": "submit"},
         {"seq": 2, "t": 0.5, "kind": "shed"}]
    )
    assert "prefill" not in durations and "decode" not in durations
    # preempted and never resumed: the stall runs to the end
    durations, _ = attribute_phases(
        [{"seq": 1, "t": 0.0, "kind": "submit"},
         {"seq": 2, "t": 1.0, "kind": "admit"},
         {"seq": 3, "t": 2.0, "kind": "prefill_done"},
         {"seq": 4, "t": 3.0, "kind": "preempt"},
         {"seq": 5, "t": 7.0, "kind": "finish"}]
    )
    assert durations["preempt_stall"] == pytest.approx(4.0)
    assert durations["decode"] == pytest.approx(1.0)


def test_attribute_phases_host_stall_windows_overlap_not_extend():
    """KV memory tiers: swap events carrying stall_s yield host_stall
    windows ending at the event time — informational overlaps (like
    tool_overlap_hidden), never subtracted from prefill/decode."""
    evs = [
        {"seq": 1, "t": 0.0, "kind": "submit"},
        {"seq": 2, "t": 1.0, "kind": "admit"},
        {"seq": 3, "t": 2.0, "kind": "prefill_done"},
        {"seq": 4, "t": 3.0, "kind": "preempt"},
        {"seq": 5, "t": 3.2, "kind": "swap_out", "detail": {"stall_s": 0.2}},
        {"seq": 6, "t": 4.5, "kind": "swap_in", "detail": {"stall_s": 0.5}},
        {"seq": 7, "t": 5.0, "kind": "prefill_done"},
        {"seq": 8, "t": 9.0, "kind": "finish"},
    ]
    durations, windows = attribute_phases(evs)
    assert durations["host_stall"] == pytest.approx(0.7)
    assert ("host_stall", 3.0, 3.2) in [
        (p, pytest.approx(a), pytest.approx(b)) for p, a, b in windows
    ] or any(p == "host_stall" and a == pytest.approx(3.0) for p, a, _ in windows)
    # the non-overlapping phases still sum to ~end-to-end
    total = sum(
        durations.get(k, 0.0)
        for k in ("queue_wait", "prefill", "decode", "preempt_stall")
    )
    assert total == pytest.approx(9.0)
    # swap events without stall detail contribute nothing
    durations, _ = attribute_phases(
        [{"seq": 1, "t": 0.0, "kind": "submit"},
         {"seq": 2, "t": 1.0, "kind": "swap_out"},
         {"seq": 3, "t": 2.0, "kind": "finish"}]
    )
    assert "host_stall" not in durations


def test_dump_crash_without_dir_returns_none(monkeypatch):
    monkeypatch.delenv("ACP_FLIGHT_DUMP_DIR", raising=False)
    rec = FlightRecorder()

    class _E:
        def stats(self):
            return {}

    assert rec.dump_crash(_E(), RuntimeError("x")) is None


# -- trace propagation through the provider: tpu client ----------------------


async def test_client_trace_context_yields_engine_child_spans():
    """TPUEngineClient advertises supports_trace_context and threads the
    caller's span context into Engine.submit — the finished request's
    phase spans land under it (the Task-trace linkage the controller
    uses)."""
    from agentcontrolplane_tpu.api.resources import BaseConfig, Message, SpanContext
    from agentcontrolplane_tpu.engine.client import TPUEngineClient

    eng = make_engine(kv_layout="slot")
    tracer = Tracer(endpoint="")
    eng.flight.tracer = tracer
    try:
        client = TPUEngineClient(eng, BaseConfig(model="tiny", max_tokens=6))
        assert client.supports_trace_context
        ctx = SpanContext(trace_id="12" * 16, span_id="34" * 8)
        msg = await client.send_request(
            [Message(role="user", content="hi")], tools=[], trace_context=ctx
        )
        assert msg.role == "assistant"
        # the future resolves before the engine thread exports spans
        deadline = time.monotonic() + 10
        spans = []
        while time.monotonic() < deadline:
            spans = tracer.spans_for_trace(ctx.trace_id)
            if spans:
                break
            time.sleep(0.02)
        names = {s.name for s in spans}
        assert {"engine.queue_wait", "engine.prefill", "engine.decode"} <= names
        assert all(s.parent_span_id == ctx.span_id for s in spans)
    finally:
        eng.stop()


def test_park_release_extends_retired_timeline_without_orphan():
    """Review fix: a park_release recorded AFTER the rid's timeline was
    retired must extend the finished timeline (discard), never re-open a
    live _by_rid entry — routine park expiries would otherwise leak one
    orphan per release and shadow the finished timeline on /timeline."""
    eng = make_engine(kv_layout="paged", park_max_s=0.05)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        fut = eng.submit("park-release-" + "y" * 20, sp, park=True)
        fut.result(timeout=60)
        _wait_timeline(eng, fut.rid)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            tl = eng.flight.timeline(fut.rid)
            if tl and tl[-1]["kind"] == "park_release":
                break
            time.sleep(0.02)
        tl = eng.flight.timeline(fut.rid)
        # the FULL lifecycle, finish and release both present — not a
        # 1-event live entry shadowing the retired record
        kinds = [e["kind"] for e in tl]
        assert kinds[-1] == "park_release" and "finish" in kinds and "submit" in kinds
        assert tl[-1]["detail"]["reason"] == "expired"
        assert eng.flight.stats()["live_requests"] == 0
    finally:
        eng.stop()


def test_attribute_phases_mid_prefill_stall_carves_prefill_not_decode():
    """Review fix: a preemption BEFORE the first token closes its stall at
    the first prefill_done — inside the prefill window — so the stall must
    subtract from prefill, not decode (which it never overlapped)."""
    durations, _ = attribute_phases(
        [{"seq": 1, "t": 0.0, "kind": "submit"},
         {"seq": 2, "t": 1.0, "kind": "admit"},
         {"seq": 3, "t": 2.0, "kind": "preempt"},      # mid-prefill victim
         {"seq": 4, "t": 12.0, "kind": "prefill_done"},  # resume's first token
         {"seq": 5, "t": 15.0, "kind": "finish"}]
    )
    assert durations["queue_wait"] == pytest.approx(1.0)
    assert durations["preempt_stall"] == pytest.approx(10.0)
    assert durations["prefill"] == pytest.approx(1.0)   # 11 - 10 stall
    assert durations["decode"] == pytest.approx(3.0)    # untouched
    total = sum(v for k, v in durations.items() if k != "tool_overlap_hidden")
    assert total == pytest.approx(15.0)
