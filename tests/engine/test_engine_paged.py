"""Engine in paged-KV mode must behave identically to slot mode (same greedy
tokens), handle page exhaustion by preemption/backpressure, and recycle pages."""

import dataclasses

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(kv_layout, **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


@pytest.fixture(scope="module")
def engines():
    slot = make_engine("slot")
    paged = make_engine("paged")
    yield slot, paged
    slot.stop()
    paged.stop()


def test_paged_matches_slot_greedy(engines):
    slot, paged = engines
    for prompt in ["hello world", "a", "xyz" * 7]:
        r_slot = slot.generate(prompt, SamplingParams(temperature=0.0, max_tokens=10))
        r_paged = paged.generate(prompt, SamplingParams(temperature=0.0, max_tokens=10))
        assert r_paged.tokens == r_slot.tokens, prompt


def test_paged_concurrent_matches_solo(engines):
    _, paged = engines
    prompts = ["aaaa", "bb", "cccccc", "d"]
    solo = [
        paged.generate(p, SamplingParams(temperature=0.0, max_tokens=6)).tokens
        for p in prompts
    ]
    futs = [paged.submit(p, SamplingParams(temperature=0.0, max_tokens=6)) for p in prompts]
    assert [f.result(timeout=120).tokens for f in futs] == solo


def test_pages_recycled_after_completion(engines):
    _, paged = engines
    free0 = paged._allocator.free_count
    futs = [paged.submit(f"req {i}", SamplingParams(temperature=0.0, max_tokens=5)) for i in range(8)]
    for f in futs:
        f.result(timeout=120)
    # allocator drains back to the initial level once everything finishes
    deadline = 100
    while paged._allocator.free_count != free0 and deadline:
        import time

        time.sleep(0.05)
        deadline -= 1
    assert paged._allocator.free_count == free0


def test_page_exhaustion_backpressure():
    # tiny pool: 9 usable pages of size 8 -> at most ~2 concurrent 32-token
    # sequences; 6 requests must still ALL complete via backpressure
    eng = make_engine("paged", kv_pages=10)
    try:
        futs = [
            eng.submit("w" * 20, SamplingParams(temperature=0.0, max_tokens=12))
            for _ in range(6)
        ]
        results = [f.result(timeout=180) for f in futs]
        assert len(results) == 6
        assert all(r.finish_reason in ("stop", "length") for r in results)
    finally:
        eng.stop()


def test_lookahead_reservation_bounds_table_uploads():
    """Page reservation runs several decode blocks ahead so the block table
    is NOT re-uploaded every dispatch (each upload is a serialized
    host->device RTT in the decode hot loop). With block K == 4 and
    lookahead 8, a 48-token generation must dirty the table ~ once per 8
    blocks, not once per block."""
    eng = make_engine("paged", page_lookahead_blocks=8)
    try:
        r = eng.generate("q" * 16, SamplingParams(temperature=0.0, max_tokens=48))
        assert len(r.tokens) >= 1
        blocks = eng.decode_steps / eng.decode_block_size
        # strictly fewer uploads than dispatched blocks; the exact count
        # depends on prefill/admission, so assert the order of magnitude
        assert eng.table_uploads <= max(3, blocks / 2), (
            f"{eng.table_uploads} uploads over ~{blocks:.0f} blocks"
        )
    finally:
        eng.stop()


def test_lookahead_one_matches_legacy_per_block_behavior():
    """page_lookahead_blocks=1 degenerates to the strict per-block
    allocation; output must be identical to the default lookahead."""
    a = make_engine("paged", page_lookahead_blocks=1)
    b = make_engine("paged", page_lookahead_blocks=8)
    try:
        ra = a.generate("lookahead", SamplingParams(temperature=0.0, max_tokens=24))
        rb = b.generate("lookahead", SamplingParams(temperature=0.0, max_tokens=24))
        assert ra.tokens == rb.tokens
    finally:
        a.stop()
        b.stop()


def test_pass1_reclaims_other_slots_lookahead_pages():
    """ADVICE r3: lookahead top-ups must never starve a strictly-fitting
    slot in a LATER round — on pass-1 exhaustion, unused lookahead pages
    (beyond other slots' strict next-block need) are clawed back before
    preempting. White-box: drain the allocator into slot 0's table as
    lookahead excess, then ask for a strict allocation for slot 1."""
    # pool sized so slot 0's max table (max_pages_per_seq) drains it exactly
    # (page 0 is the reserved trash page)
    eng = make_engine("paged", kv_pages=9)
    try:
        K = eng.decode_block_size
        strict0 = -(-(16 + K) // eng.page_size)  # slot 0's strict need
        import types

        eng._slots[0] = types.SimpleNamespace(  # white-box stub
            parked=False, prefilling=False
        )
        eng._seq_lens[0] = 16
        # hand slot 0 its strict pages plus the rest of the pool as lookahead
        table = eng._allocator.alloc(strict0)
        table += eng._allocator.alloc(
            min(eng._allocator.free_count, eng.max_pages_per_seq - strict0)
        )
        eng._slot_pages[0] = list(table)
        eng._block_tables[0, : len(table)] = table
        assert eng._allocator.free_count == 0

        got = eng._alloc_reclaiming_lookahead(2, requester=1)
        assert got is not None and len(got) == 2
        # slot 0 kept exactly its strict need; the excess was reclaimed
        assert len(eng._slot_pages[0]) == strict0
        assert eng._tables_dirty

        # nothing left to reclaim below strict need -> honest failure
        assert eng._alloc_reclaiming_lookahead(10_000, requester=1) is None
        assert len(eng._slot_pages[0]) == strict0
    finally:
        eng._slots.clear()
        eng.stop()
