"""Gemma-1 family: exact logits vs transformers' GemmaForCausalLM (the
architecture deltas over Llama: GeGLU, (1+w) RMSNorm, sqrt(dim)-scaled
embeddings, explicit head_dim / MQA, tied embeddings)."""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.engine.weights import config_from_hf, params_from_state_dict
from agentcontrolplane_tpu.models.llama import PRESETS, forward

TINY_GEMMA = dict(
    vocab_size=256,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=1,  # MQA like gemma-2b
    head_dim=32,  # != hidden/heads (16): exercises the override
    intermediate_size=128,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    max_position_embeddings=128,
    hidden_activation="gelu_pytorch_tanh",
)


@pytest.fixture(scope="module")
def gemma_model_and_params(tmp_path_factory):
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_config = GemmaConfig(**TINY_GEMMA, attn_implementation="eager")
    torch.manual_seed(0)
    model = GemmaForCausalLM(hf_config).eval()

    path = tmp_path_factory.mktemp("gemma") / "config.json"
    cfg_doc = dict(TINY_GEMMA)
    cfg_doc["model_type"] = "gemma"
    path.write_text(json.dumps(cfg_doc))
    config = config_from_hf(str(path))
    assert config.hidden_act == "gelu_tanh"
    assert config.norm_plus_one and config.embed_scale and config.tie_embeddings
    assert config.head_dim == 32 and config.n_kv_heads == 1
    config = dataclasses.replace(config, dtype=jnp.float32)
    params = params_from_state_dict(model.state_dict(), config)
    return model, params, config


def test_gemma_logits_match_hf(gemma_model_and_params):
    torch = pytest.importorskip("torch")
    model, params, config = gemma_model_and_params
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, TINY_GEMMA["vocab_size"], (2, 24))
    with torch.no_grad():
        ref = model(torch.asarray(tokens)).logits.float().numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, dtype=jnp.int32), config))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gemma_serves_in_engine(gemma_model_and_params):
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer

    _, params, config = gemma_model_and_params
    # MQA: 1 kv head can't shard over tp — serve tp=1 (documented)
    eng = Engine(
        config=config, params=params, tokenizer=ByteTokenizer(),
        mesh=jax.sharding.Mesh(jax.devices()[:1], ("tp",)),
        max_slots=2, max_ctx=128, prefill_buckets=(64, 128), decode_block_size=4,
    )
    eng.start()
    try:
        r = eng.generate("hello gemma", SamplingParams(temperature=0.0, max_tokens=8))
        assert len(r.tokens) >= 1
        r2 = eng.generate("hello gemma", SamplingParams(temperature=0.0, max_tokens=8))
        assert r.tokens == r2.tokens
    finally:
        eng.stop()


def test_gemma_presets_shapes():
    for name in ("gemma-2b", "gemma-7b"):
        c = PRESETS[name]
        assert c.head_dim == 256 and c.tie_embeddings and c.norm_plus_one
