"""TPUEngineClient's two-phase timeout (ADVICE r3: the 30 s request budget
must not be consumed by admission-queue wait — under saturation or cold
compiles every request would 504 into timeout-retry churn).

Phase 1 (submit -> slot admission) is bounded by queue_timeout_seconds;
phase 2 (admission -> completion) by request_timeout_seconds. These tests
drive ``_await_result`` with stub futures — no engine, no device.
"""

from __future__ import annotations

import asyncio
import threading
from concurrent.futures import Future

import pytest

from agentcontrolplane_tpu.api.resources import BaseConfig
from agentcontrolplane_tpu.engine.client import TPUEngineClient


def make_client(request_timeout_s: float, queue_timeout_s: float) -> TPUEngineClient:
    return TPUEngineClient(
        engine=object(),  # _await_result never touches the engine
        params=BaseConfig(model="stub"),
        request_timeout_s=request_timeout_s,
        queue_timeout_s=queue_timeout_s,
    )


def make_future(admitted: bool | None = False) -> Future:
    """admitted=None -> legacy future without the attribute."""
    fut: Future = Future()
    if admitted is not None:
        # concurrent Future, not an Event: the client bridges it with
        # wrap_future so queued requests park no executor threads
        fut.admitted = Future()  # type: ignore[attr-defined]
        if admitted:
            fut.admitted.set_result(True)  # type: ignore[attr-defined]
    return fut


async def test_queue_wait_does_not_consume_generation_budget():
    """Admission arrives AFTER the request timeout would have expired; the
    generation still completes because its clock starts at admission."""
    client = make_client(request_timeout_s=0.4, queue_timeout_s=30.0)
    fut = make_future(admitted=False)

    def engine_side():
        # queued for longer than request_timeout_s...
        threading.Event().wait(0.6)
        fut.admitted.set_result(True)
        threading.Event().wait(0.2)  # then generates well inside the budget
        fut.set_result("generated")

    t = threading.Thread(target=engine_side, daemon=True)
    t.start()
    assert await client._await_result(fut) == "generated"
    t.join()


async def test_queue_timeout_expires_with_queue_message():
    client = make_client(request_timeout_s=30.0, queue_timeout_s=0.2)
    fut = make_future(admitted=False)
    with pytest.raises(asyncio.TimeoutError, match="queue wait"):
        await client._await_result(fut)


async def test_generation_timeout_after_admission():
    client = make_client(request_timeout_s=0.2, queue_timeout_s=30.0)
    fut = make_future(admitted=True)
    with pytest.raises(asyncio.TimeoutError, match="after slot admission"):
        await client._await_result(fut)


async def test_completion_while_queued_short_circuits():
    """Fast failure paths complete the future without ever admitting."""
    client = make_client(request_timeout_s=30.0, queue_timeout_s=30.0)
    fut = make_future(admitted=False)
    threading.Timer(0.1, lambda: fut.set_result("early")).start()
    assert await client._await_result(fut) == "early"


async def test_future_without_admitted_attr_uses_request_timeout():
    """Futures from engines predating the admitted event still time out."""
    client = make_client(request_timeout_s=0.2, queue_timeout_s=30.0)
    fut = make_future(admitted=None)
    with pytest.raises(asyncio.TimeoutError):
        await client._await_result(fut)


def test_requests_drained_at_stop_fail_instead_of_hanging():
    """A request racing stop() into the same queue drain must have its
    future failed (review finding: the drained-but-unadmitted list was
    discarded, hanging the caller forever)."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    eng = Engine(
        config=dataclasses.replace(PRESETS["tiny"], vocab_size=512),
        tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 1}, devices=jax.devices()[:1]),
        max_slots=2, max_ctx=64, prefill_buckets=(32,),
        decode_block_size=4, prefix_cache_entries=0, seed=0,
    )
    eng.start()
    with eng.hold_admission():  # keep the request in the queue/waiting
        fut = eng.submit("hang?", SamplingParams(temperature=0.0, max_tokens=4))
        eng.stop()
    with pytest.raises((RuntimeError, asyncio.CancelledError, Exception)):
        fut.result(timeout=30)
