"""Mutation harness for the armed runtime invariant checker
(engine/invariants.py).

A checker that never fires proves nothing: each test seeds one HISTORICAL
bug class into a healthy engine's state and asserts the audit catches it —

- **mirror drift** (the PR 6 class: stats counters diverging from the slot
  dict) via direct corruption AND end-to-end via the
  ``engine.invariant_break`` fault site (armed engine crashes with
  ``InvariantViolation``, callers fail loudly, ``ensure_running`` recovers);
- **refcount leak / conservation break** (the PR 5 class: reclaim stripping
  pages an in-flight dispatch was granted);
- **parked-KV coverage break** (the PR 7 garbage-lane class in its
  host-observable form: a parked slot no longer holding exactly its
  prompt-covering pages means adoption would resume over corrupt KV);
- **quantized scale-row corruption** (ISSUE 14: int8 KV pages whose
  per-page scale ownership leaks past a free, vanishes under a live
  allocation, or shears off the cache structurally — each means later
  reads dequantize through wrong/unowned scale storage).

Every corruption is reverted so the module-scoped engine stays healthy
between tests; the audit itself is read-only.
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.invariants import (
    InvariantViolation,
    check_engine_invariants,
    verify_engine,
)
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout="paged",
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def eng():
    """One armed paged engine, warmed with real traffic that leaves a
    parked slot and live prefix-cache entries behind — the audit must hold
    on the REAL state shapes, not an empty engine. The violations counter
    is process-global, and earlier suites deliberately trip it (the flight
    recorder's crash-dump test arms engine.invariant_break) — snapshot it
    so this module asserts on ITS engine's delta, not absolutes."""
    e = make_engine(spec_len=4, prefill_chunk=16)
    e.violations0 = counter("acp_engine_invariant_violations_total")
    sp = SamplingParams(temperature=0.0, max_tokens=10)
    futs = [
        e.submit(f"hello world {i} " * 3, sp, park=(i == 0)) for i in range(4)
    ]
    for f in futs:
        assert f.result(timeout=600).finish_reason in ("stop", "length")
    yield e
    e.stop()


def _settle(e: Engine) -> None:
    """Let the engine loop drain to idle so test-thread reads don't race a
    dispatch in flight."""
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (e._has_work() or len(e._waiting)):
        time.sleep(0.01)
    time.sleep(0.05)


def test_clean_engine_audits_clean_and_counts_checks(eng):
    _settle(eng)
    assert eng._parked_count == 1  # the fixture's parked conversation
    assert verify_engine(eng) == []
    # the engine ran armed through the fixture's traffic: every dispatch
    # cycle audited, none tripped
    assert counter("acp_engine_invariant_checks_total") > 0
    assert counter("acp_engine_invariant_violations_total") == eng.violations0


def test_mirror_drift_is_detected(eng):
    _settle(eng)
    eng._parked_count += 1
    try:
        problems = verify_engine(eng)
    finally:
        eng._parked_count -= 1
    assert any("mirror drift" in p and "_parked_count" in p for p in problems)

    eng._prefilling_count += 1
    try:
        problems = verify_engine(eng)
    finally:
        eng._prefilling_count -= 1
    assert any("_prefilling_count" in p for p in problems)
    assert verify_engine(eng) == []


def test_refcount_leak_and_conservation_break_are_detected(eng):
    _settle(eng)
    refs = eng._allocator._refs
    page = next(iter(refs))
    refs[page] += 1  # a reference nothing owns: the page can never pool
    try:
        problems = verify_engine(eng)
    finally:
        refs[page] -= 1
    assert any("refcount leak" in p for p in problems)

    stolen = eng._allocator._free.pop()  # page vanishes from accounting
    try:
        problems = verify_engine(eng)
    finally:
        eng._allocator._free.append(stolen)
    assert any("vanished from accounting" in p for p in problems)
    assert verify_engine(eng) == []


def test_parked_kv_coverage_break_is_detected(eng):
    _settle(eng)
    slot = next(s for s, sl in eng._slots.items() if sl.parked)

    # page list no longer covers the prompt cut (the host-observable shape
    # of the PR 7 garbage-lane corruption of parked prompt KV)
    page = eng._slot_pages[slot].pop()
    try:
        problems = verify_engine(eng)
    finally:
        eng._slot_pages[slot].append(page)
    assert any("parked slot" in p for p in problems)

    # seq_len mirror diverging from the adoption cut
    cut = int(eng._seq_lens[slot])
    eng._seq_lens[slot] = cut + 1
    try:
        problems = verify_engine(eng)
    finally:
        eng._seq_lens[slot] = cut
    assert any("park_cut" in p for p in problems)
    assert verify_engine(eng) == []


def test_check_raises_and_counts(eng):
    _settle(eng)
    check_engine_invariants(eng)  # healthy: no raise
    before = counter("acp_engine_invariant_violations_total")
    eng._parked_count += 1
    try:
        with pytest.raises(InvariantViolation, match="mirror drift"):
            check_engine_invariants(eng)
    finally:
        eng._parked_count -= 1
    assert counter("acp_engine_invariant_violations_total") > before


def test_host_resident_page_leak_is_detected():
    """PR 11 corruption class 1: KV swapped out to the host tier whose
    bytes drift from the pool's entry accounting — RAM that can never be
    restored or reclaimed. Seeded both ways: counter drift and an entry
    vanishing behind the counter's back."""
    e = make_engine(kv_pages=10, host_kv_bytes=1 << 22)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        with e.hold_admission():  # oversubscribe -> preempt -> swap out
            futs = [e.submit(ch * 20, sp) for ch in "abcdef"]
        for f in futs:
            f.result(timeout=180)
        assert e.kv_swap_outs >= 1
        _settle(e)
        # a park-expiry swap may land an entry; make one deterministically
        if not len(e._host_pool):
            from agentcontrolplane_tpu.ops.paged import HostKVEntry
            import numpy as np

            e._host_pool.put(HostKVEntry(
                rid="seed", tokens=tuple(range(16)),
                k=np.zeros((2, 16, 2, 8), dtype=np.float32),
                v=np.zeros((2, 16, 2, 8), dtype=np.float32),
            ))
            e._publish_memory_state()
        assert verify_engine(e) == []

        e._host_pool.used_bytes += 123  # bytes with no entry: the leak
        try:
            problems = verify_engine(e)
        finally:
            e._host_pool.used_bytes -= 123
        assert any("host KV pool leak" in p for p in problems)
        # the engine mirror must also be flagged (stats() serves it)
        assert any("_host_kv_used" in p for p in problems)

        rid, entry = next(iter(e._host_pool._entries.items()))
        del e._host_pool._entries[rid]  # entry gone, bytes still counted
        try:
            problems = verify_engine(e)
        finally:
            e._host_pool._entries[rid] = entry
        assert any("host KV pool leak" in p for p in problems)
        assert verify_engine(e) == []
    finally:
        e.stop()


def test_quantized_scale_row_corruption_classes_are_detected():
    """The quantized-page accounting class (ISSUE 14): an engine serving
    int8 KV must own exactly one set of scale rows per allocated page.
    Both corruption directions — a scale row leaking past its page's
    free, and an allocated page whose scale ownership vanished — plus the
    structural cache coupling (scale twins sheared off, scale storage on
    a knobs-off engine) must all trip the audit."""
    e = make_engine(quantize_kv=True)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        assert e.generate("warm quantized pages", sp).finish_reason in (
            "stop", "length",
        )
        _settle(e)
        assert verify_engine(e) == []

        scale_pages = e._allocator._scale_pages
        # direction 1: scale rows owned for a page that was freed
        stale = max(set(range(1, e.num_pages)) - set(e._allocator._refs))
        scale_pages.add(stale)
        try:
            problems = verify_engine(e)
        finally:
            scale_pages.discard(stale)
        assert any("scale-row leak" in p for p in problems)

        # direction 2: an allocated page without owned scale rows — seed a
        # live allocation first (the idle engine may hold none)
        pages = e._allocator.alloc(1)
        try:
            scale_pages.discard(pages[0])
            problems = verify_engine(e)
            scale_pages.add(pages[0])
        finally:
            e._allocator.free(pages)
        assert any("without owned scale rows" in p for p in problems)

        # structural coupling: scale twin sheared off its values
        ks = e.cache.pop("ks")
        try:
            problems = verify_engine(e)
        finally:
            e.cache["ks"] = ks
        assert any("cache carries keys" in p for p in problems)
        assert verify_engine(e) == []
    finally:
        e.stop()


def test_off_knob_engine_with_scale_storage_is_detected(eng):
    """The purity direction: a knobs-off engine carrying scale storage is
    itself a violation (the bit-identical plain path must have none)."""
    _settle(eng)
    import jax.numpy as jnp

    eng.cache["ks"] = jnp.zeros((1,), dtype=jnp.float32)
    try:
        problems = verify_engine(eng)
    finally:
        del eng.cache["ks"]
    assert any("quantize_kv off" in p for p in problems)
    assert verify_engine(eng) == []


def test_shared_page_refcount_drift_is_detected(eng):
    """PR 11 corruption class 2: a dedup'd/shared page freed while a
    second owner still holds it — the next free would pool a live page and
    hand it to two sequences. The fixture's parked slot + its prefix-cache
    entry share pages (refcount 2), so dropping one ref leaves unshared
    multi-ownership plus shared-counter drift."""
    _settle(eng)
    _, refs = eng._allocator.audit()
    shared_pg = next(pg for pg, r in refs.items() if r > 1)
    eng._allocator.free([shared_pg])  # one owner's ref silently dropped
    try:
        problems = verify_engine(eng)
    finally:
        eng._allocator.share([shared_pg])  # restore the dropped reference
    assert any("owners but refcount" in p for p in problems)
    assert verify_engine(eng) == []

    # incremental shared-counter drift is caught independently
    eng._allocator._shared += 1
    try:
        problems = verify_engine(eng)
    finally:
        eng._allocator._shared -= 1
    assert any("shared_count" in p for p in problems)
    # and the stats() mirror drift class
    eng._prefix_shared_pages += 1
    try:
        problems = verify_engine(eng)
    finally:
        eng._prefix_shared_pages -= 1
    assert any("_prefix_shared_pages" in p for p in problems)
    assert verify_engine(eng) == []


def test_goodput_ledger_conservation_break_is_detected(eng):
    """ISSUE 12 corruption class: a dispatch site adding compute without
    classifying it (or a non-zero-sum reclassify) breaks the goodput
    ledger the scheduler autopilot will steer by. Seeded three ways:
    unclassified compute, a negative waste counter, and negative
    goodput."""
    _settle(eng)
    assert verify_engine(eng) == []
    prof = eng.profiler

    prof._computed += 7  # compute nothing classified
    try:
        problems = verify_engine(eng)
    finally:
        prof._computed -= 7
    assert any("goodput ledger conservation broken" in p for p in problems)

    pad0, comp0 = prof._waste["pad_bucket"], prof._computed
    prof._waste["pad_bucket"] = -2
    prof._computed = comp0 - pad0 - 2  # keep the sum balanced: only negativity trips
    try:
        problems = verify_engine(eng)
    finally:
        prof._waste["pad_bucket"], prof._computed = pad0, comp0
    assert any("negative waste-cause counters" in p for p in problems)

    good0, comp0 = prof._goodput, prof._computed
    prof._goodput = -1
    prof._computed = -1 + sum(prof._waste.values())  # balanced but negative
    try:
        problems = verify_engine(eng)
    finally:
        prof._goodput, prof._computed = good0, comp0
    assert any("goodput ledger negative" in p for p in problems)
    assert verify_engine(eng) == []


def test_invariant_break_fault_trips_end_to_end():
    """The deterministic fault site corrupts a mirror inside the engine
    loop; the armed checker must crash the engine, fail the in-flight
    caller loudly, and leave the engine recoverable."""
    eng = make_engine()
    try:
        # healthy round trip first (also compiles the programs)
        assert eng.generate("ab", SamplingParams(max_tokens=2)).tokens
        FAULTS.arm("engine.invariant_break")
        fut = eng.submit("hello there", SamplingParams(temperature=0.0, max_tokens=48))
        with pytest.raises(RuntimeError, match="invariant"):
            fut.result(timeout=600)
        assert eng._crashed
        # phase-machine posture: rebuild serving state and carry on
        assert eng.ensure_running()
        out = eng.generate("hello again", SamplingParams(max_tokens=4))
        assert out.finish_reason in ("stop", "length")
        assert verify_engine(eng) == []
    finally:
        eng.stop()


def test_disarmed_fault_site_is_inert():
    """Arming engine.invariant_break against a DISARMED engine must not
    corrupt anything: the site is gated on check_invariants."""
    eng = make_engine(check_invariants=False)
    try:
        FAULTS.arm("engine.invariant_break")
        out = eng.generate("hello", SamplingParams(temperature=0.0, max_tokens=8))
        assert out.finish_reason in ("stop", "length")
        assert verify_engine(eng) == []  # mirrors untouched
    finally:
        eng.stop()
