"""Real-TPU validation, opt-in via ACP_TEST_TPU=1 (VERDICT r1 #2).

These run against the actual chip through the axon tunnel (NOT the forced
CPU backend the rest of the suite uses): compiled-mode Pallas paged
attention vs the XLA reference on-device, TPU-shaped tile sizes, and a
slot-vs-paged engine equivalence on hardware.

    ACP_TEST_TPU=1 python -m pytest tests/engine/test_tpu_hardware.py -q
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("ACP_TEST_TPU"),
    reason="set ACP_TEST_TPU=1 to run against the real TPU",
)


@pytest.fixture(scope="module")
def tpu():
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip(f"no TPU backend (got {jax.default_backend()})")
    return jax.devices()[0]


def _setup_tpu_shapes(seed=0, S=8, H=8, Hkv=8, d=128, P=16, max_pages=8, num_pages=128):
    """TPU-native tile sizes: d=128 lanes, P a multiple of the sublane tile."""
    import jax.numpy as jnp

    from agentcontrolplane_tpu.ops.paged import PageAllocator, TRASH_PAGE

    rng = np.random.default_rng(seed)
    seq_lens = rng.integers(1, max_pages * P, size=S).astype(np.int32)
    q = jnp.asarray(rng.normal(size=(S, H, d)), dtype=jnp.float32)
    k_pages = np.zeros((num_pages, P, Hkv, d), dtype=np.float32)
    v_pages = np.zeros((num_pages, P, Hkv, d), dtype=np.float32)
    alloc = PageAllocator(num_pages)
    tables = np.full((S, max_pages), TRASH_PAGE, dtype=np.int32)
    for s in range(S):
        n = -(-int(seq_lens[s]) // P)
        pages = alloc.alloc(n)
        tables[s, :n] = pages
        kv = rng.normal(size=(2, int(seq_lens[s]), Hkv, d)).astype(np.float32)
        for j, page in enumerate(pages):
            lo, hi = j * P, min((j + 1) * P, int(seq_lens[s]))
            k_pages[page, : hi - lo] = kv[0][lo:hi]
            v_pages[page, : hi - lo] = kv[1][lo:hi]
    return (
        q,
        jnp.asarray(k_pages),
        jnp.asarray(v_pages),
        jnp.asarray(tables),
        jnp.asarray(seq_lens),
    )


def test_compiled_pallas_paged_attention_matches_reference(tpu):
    """The double-buffered DMA kernel, COMPILED on hardware (not interpret
    mode), must agree with the XLA gather reference."""
    import jax

    from agentcontrolplane_tpu.ops.paged import paged_decode_attention_reference
    from agentcontrolplane_tpu.ops.pallas.paged_attention import paged_decode_attention

    q, k_pages, v_pages, tables, seq_lens = _setup_tpu_shapes()
    ref = jax.jit(paged_decode_attention_reference)(q, k_pages, v_pages, tables, seq_lens)
    out = jax.jit(paged_decode_attention)(q, k_pages, v_pages, tables, seq_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_compiled_pallas_gqa_shapes(tpu):
    import jax

    from agentcontrolplane_tpu.ops.paged import paged_decode_attention_reference
    from agentcontrolplane_tpu.ops.pallas.paged_attention import paged_decode_attention

    q, k_pages, v_pages, tables, seq_lens = _setup_tpu_shapes(
        seed=1, S=4, H=32, Hkv=8, d=128, P=32, max_pages=4, num_pages=64
    )
    ref = jax.jit(paged_decode_attention_reference)(q, k_pages, v_pages, tables, seq_lens)
    out = jax.jit(paged_decode_attention)(q, k_pages, v_pages, tables, seq_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_compiled_pallas_gemma_geometry(tpu):
    """head_dim 256 (gemma's head_dim_override) with MQA compiles and
    matches — the engine's Pallas gate admits head_dim % 128 == 0."""
    import jax

    from agentcontrolplane_tpu.ops.paged import paged_decode_attention_reference
    from agentcontrolplane_tpu.ops.pallas.paged_attention import paged_decode_attention

    q, k_pages, v_pages, tables, seq_lens = _setup_tpu_shapes(
        seed=2, S=4, H=8, Hkv=1, d=256, P=16, max_pages=4, num_pages=32
    )
    ref = jax.jit(paged_decode_attention_reference)(q, k_pages, v_pages, tables, seq_lens)
    out = jax.jit(paged_decode_attention)(q, k_pages, v_pages, tables, seq_lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_compiled_pallas_cache_plus_new(tpu):
    """The serving hot-path form (kernel (acc,m,l) + external self-term
    merge) compiled on hardware == the XLA reference."""
    import jax
    import jax.numpy as jnp

    from agentcontrolplane_tpu.ops.paged import (
        paged_decode_attention_reference_cache_plus_new,
    )
    from agentcontrolplane_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_cache_plus_new,
    )

    q, k_pages, v_pages, tables, seq_lens = _setup_tpu_shapes(seed=3)
    rng = np.random.default_rng(13)
    S = q.shape[0]
    Hkv, d = k_pages.shape[2], k_pages.shape[3]
    k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    ref = jax.jit(paged_decode_attention_reference_cache_plus_new)(
        q, k_pages, v_pages, tables, seq_lens, k_new, v_new
    )
    out = jax.jit(paged_decode_attention_cache_plus_new)(
        q, k_pages, v_pages, tables, seq_lens, k_new, v_new
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_engine_slot_and_paged_agree_on_tpu(tpu):
    """Greedy decode through BOTH kv layouts on hardware must produce the
    same tokens (the paged path uses the compiled Pallas kernel: engine
    _use_pallas is True on the tpu backend)."""
    import dataclasses

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS

    # hardware-native geometry (head_dim 128) so the paged engine takes the
    # compiled Pallas path — the tiny CPU config's head_dim 16 would fall
    # back to the XLA reference and test nothing new here
    cfg = dataclasses.replace(
        PRESETS["tiny"], vocab_size=512, dim=512, n_heads=4, n_kv_heads=2,
        head_dim_override=128,
    )
    results = {}
    for layout in ("slot", "paged"):
        eng = Engine(
            config=cfg,
            tokenizer=ByteTokenizer(),
            max_slots=2,
            max_ctx=128,
            prefill_buckets=(64, 128),
            decode_block_size=8,
            kv_layout=layout,
            seed=0,
        )
        assert layout == "slot" or eng._use_pallas, "paged on TPU must compile Pallas"
        eng.start()
        try:
            results[layout] = eng.generate(
                "the quick brown fox", SamplingParams(temperature=0.0, max_tokens=24)
            ).tokens
        finally:
            eng.stop()
    assert results["slot"] == results["paged"]
