"""Compute efficiency observatory, engine-integrated (ISSUE 12):

- profiler on vs off is byte-identical on greedy outputs (both KV layouts,
  spec + chunking on) — the observatory measures, never steers;
- per-program dispatch telemetry populates for the real program zoo;
- the cold-compile observatory: a deliberately un-prewarmed shape after
  prewarm-complete fires the event + counter, and a fully-prewarmed run
  reports zero serving-time cold compiles;
- the goodput/waste ledger conserves (computed == goodput + Σ waste) under
  the stress/fault matrix (preempt + spec_mismatch + host_swap_error) with
  the armed invariant checker auditing every cycle;
- the prewarm coverage gap is data, not a log line (satellite: a provoked
  "batch never formed" records a flight event + counter).
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.invariants import verify_engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str, **labels) -> float:
    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    return m.values.get(tuple(sorted(labels.items())), 0.0)


def _settle(e: Engine) -> None:
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and (e._has_work() or len(e._waiting)):
        time.sleep(0.01)
    time.sleep(0.05)


def _conserved(e: Engine) -> dict:
    led = e.profiler.ledger()
    assert led["computed"] == led["goodput"] + sum(led["waste"].values()), led
    return led


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- byte identity: the observatory measures, never steers --------------------


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_profiler_on_off_greedy_byte_identity(kv_layout):
    """Same seed, same prompts, spec + chunked prefill on: the engine with
    the profiler enabled must emit bit-for-bit the tokens of the engine
    with it disabled — the hooks never touch dispatch inputs/outputs."""
    prompts = ["hello profiler " + c * 9 for c in "abc"]
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    outs = []
    for enabled in (True, False):
        eng = make_engine(kv_layout=kv_layout, spec_len=4, prefill_chunk=16)
        eng.profiler.enabled = enabled
        try:
            futs = [eng.submit(p, sp) for p in prompts]
            outs.append([f.result(timeout=600).tokens for f in futs])
        finally:
            eng.stop()
    assert outs[0] == outs[1]


# -- per-program telemetry + ledger -------------------------------------------


def test_program_stats_and_ledger_populate():
    # megastep OFF: this test pins the SPLIT dispatch zoo (chunk + decode
    # program keys), which remains the fused path's shape-bound fallback;
    # the fused zoo is pinned by tests/engine/test_megastep.py
    eng = make_engine(kv_layout="paged", spec_len=4, prefill_chunk=16, megastep=False)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=10)
        futs = [eng.submit(f"telemetry {i} " * 3, sp) for i in range(4)]
        for f in futs:
            f.result(timeout=600)
        _settle(eng)
        perf = eng.stats()["perf"]
        assert perf["enabled"] is True
        programs = perf["programs"]
        # the chunked paged engine's zoo: chunk dispatches + final-chunk
        # continuations + decode blocks (spec verify fires only when the
        # drafter proposes — not asserted, scheduling-dependent)
        assert any(k.startswith("chunk[paged,") for k in programs)
        assert any(k.startswith("decode[paged,") for k in programs)
        for p in programs.values():
            assert p["dispatches"] > 0
            assert p["host_ms_mean"] >= 0.0
            assert p["device_samples"] >= 1  # first dispatch always samples
            assert p["real_tokens"] + p["padded_tokens"] >= 0
        led = _conserved(eng)
        assert led["computed"] > 0 and led["goodput"] > 0
        g = perf["goodput"]
        assert 0.0 < g["ratio"] <= 1.0
        # program keys ride the flight dispatch events too
        blocks = eng.flight.events(kind="decode_block")
        assert blocks and all(
            e["detail"]["program"].startswith("decode[paged,")
            for e in blocks
        )
    finally:
        eng.stop()


def test_dispatch_seconds_histogram_exported():
    eng = make_engine(kv_layout="slot")
    try:
        eng.generate("histogram", SamplingParams(temperature=0.0, max_tokens=6))
        _settle(eng)
        keys = [k for k in eng.profiler.stats()["programs"] if k.startswith("decode[")]
        assert keys
        count, window = REGISTRY.series_window(
            "acp_engine_dispatch_seconds", {"program": keys[0]}
        )
        assert count > 0 and window
    finally:
        eng.stop()


# -- cold-compile observatory -------------------------------------------------


def test_unprewarmed_shape_fires_cold_compile_event_and_counter():
    """Dispatching a shape never seen before prewarm-complete must surface
    as a cold_compile flight event + acp_engine_cold_compiles_total."""
    eng = make_engine(kv_layout="slot", prefix_cache_entries=0)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=5)
        eng.generate("x" * 10, sp)  # compiles prefill[32x1] + decode widths
        _settle(eng)
        eng.profiler.mark_prewarmed()
        before = counter("acp_engine_cold_compiles_total")
        assert eng.profiler.stats()["cold_compiles"]["serving"] == 0
        # bucket 64 was never dispatched: a deliberately un-prewarmed shape
        eng.generate("y" * 40, sp)
        _settle(eng)
        cold = eng.profiler.stats()["cold_compiles"]
        assert cold["serving"] >= 1
        assert any(
            ev["program"].startswith("prefill[slot,64x1") and ev["wall_s"] > 0
            for ev in cold["events"]
        )
        assert counter("acp_engine_cold_compiles_total") > before
        evs = eng.flight.events(kind="cold_compile")
        assert evs and any(
            e["detail"]["program"].startswith("prefill[slot,64x1") for e in evs
        )
    finally:
        eng.stop()


def test_fully_prewarmed_engine_reports_zero_cold_compiles():
    """After Engine.prewarm() the documented coverage holds: serving
    requests whose shapes prewarm compiled must record NO serving-time
    cold compiles."""
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=2,
        max_ctx=64,
        prefill_buckets=(16, 32),
        decode_block_size=4,
        kv_layout="slot",
        prefix_cache_entries=0,  # prefix extract programs compile per cut
        check_invariants=True,
    )
    eng.start()
    try:
        eng.prewarm(constrained=False)
        assert eng.profiler.stats()["prewarmed"] is True
        assert eng.profiler.stats()["cold_compiles"]["serving"] == 0
        sp = SamplingParams(temperature=0.0, max_tokens=9)
        futs = [eng.submit("c" * 10, sp), eng.submit("d" * 20, sp)]
        for f in futs:
            f.result(timeout=600)
        _settle(eng)
        cold = eng.profiler.stats()["cold_compiles"]
        assert cold["serving"] == 0, cold["events"]
        assert counter("acp_engine_prewarm_gaps_total", phase="plain") == 0.0
    finally:
        eng.stop()


# -- satellite: the prewarm coverage gap is data ------------------------------


class _DropSet(set):
    """A dispatch record that 'loses' one batch size — the deterministic
    provocation of the 'batch never formed' retry exhaustion."""

    def __init__(self, drop):
        super().__init__()
        self._drop = drop

    def add(self, item):
        if item != self._drop:
            super().add(item)


def test_prewarm_gap_records_flight_event_and_counter():
    eng = make_engine(kv_layout="slot", prefill_chunk=16, prefix_cache_entries=0)
    try:
        eng._chunk_batch_sizes = _DropSet(2)  # B=2 can never verify
        before = counter("acp_engine_prewarm_gaps_total", phase="chunked")
        eng._prewarm_chunked(constrained=False)
        assert counter("acp_engine_prewarm_gaps_total", phase="chunked") == before + 1
        evs = eng.flight.events(kind="prewarm_gap")
        assert evs
        assert evs[-1]["detail"] == {"phase": "chunked", "B": 2}
    finally:
        eng.stop()


# -- conservation under the stress/fault matrix -------------------------------


def test_token_conservation_under_fault_matrix():
    """preempt + spec_mismatch + host_swap_error, armed invariants (the
    audit now includes the profiler ledger): every request completes, the
    audit stays clean, conservation holds, and the waste the faults
    manufactured is attributed to real causes."""
    eng = make_engine(
        kv_layout="paged", kv_pages=24, spec_len=4, prefill_chunk=16,
        host_kv_bytes=1 << 22, check_invariants=True,
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        # warm pass compiles the zoo so the fault legs measure scheduling
        for f in [eng.submit("warm " + c * 16, sp) for c in "ab"]:
            f.result(timeout=600)
        _settle(eng)
        FAULTS.arm("engine.force_preempt", after_steps=2)
        FAULTS.arm("engine.spec_mismatch", times=1)
        FAULTS.arm("engine.host_swap_error", times=2)
        with eng.hold_admission():  # oversubscribe the tiny pool
            futs = [eng.submit(ch * 24, sp) for ch in "cdefgh"]
        for f in futs:
            assert f.result(timeout=600).finish_reason in ("stop", "length")
        _settle(eng)
        assert verify_engine(eng) == []
        led = _conserved(eng)
        assert led["computed"] > 0
        waste = led["waste"]
        # pool pressure + the armed faults must have manufactured real
        # attributed waste (which bucket depends on where the fault popped)
        assert eng.preemptions > 0
        assert (
            waste["preempt_discard"] + waste["swap_recompute"]
            + waste["spec_rejected"]
        ) > 0
        # the perf payload reports the same ledger the audit verified
        g = eng.stats()["perf"]["goodput"]
        assert g["computed"] == led["computed"]
        assert g["waste"] == waste
        assert g["ratio"] == pytest.approx(
            led["goodput"] / led["computed"], abs=1e-4
        )
    finally:
        eng.stop()
