"""Qwen2 family (qkv_bias) correctness vs HF transformers."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.engine.weights import config_from_hf, params_from_state_dict
from agentcontrolplane_tpu.models.llama import LlamaConfig, forward, init_params
from agentcontrolplane_tpu.parallel.mesh import make_mesh, param_shardings

TINY_QWEN = LlamaConfig(
    vocab_size=256,
    dim=64,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    ffn_dim=128,
    max_seq_len=128,
    rope_theta=10000.0,
    qkv_bias=True,
    dtype=jnp.float32,
)


def test_qwen2_logits_match_hf():
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_config = Qwen2Config(
        vocab_size=TINY_QWEN.vocab_size,
        hidden_size=TINY_QWEN.dim,
        num_hidden_layers=TINY_QWEN.n_layers,
        num_attention_heads=TINY_QWEN.n_heads,
        num_key_value_heads=TINY_QWEN.n_kv_heads,
        intermediate_size=TINY_QWEN.ffn_dim,
        rms_norm_eps=TINY_QWEN.norm_eps,
        rope_theta=TINY_QWEN.rope_theta,
        max_position_embeddings=TINY_QWEN.max_seq_len,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = Qwen2ForCausalLM(hf_config).eval()
    params = params_from_state_dict(model.state_dict(), TINY_QWEN)
    assert "bq" in params["layers"]  # biases loaded
    tokens = np.random.default_rng(0).integers(0, TINY_QWEN.vocab_size, size=(2, 13))
    with torch.no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, dtype=jnp.int32), TINY_QWEN))
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)


def test_qwen2_config_from_hf_detects_bias(tmp_path):
    import json

    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(
        json.dumps(
            {
                "model_type": "qwen2",
                "vocab_size": 1000,
                "hidden_size": 64,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
                "num_key_value_heads": 2,
                "intermediate_size": 128,
                "rope_theta": 1000000.0,
            }
        )
    )
    cfg = config_from_hf(str(cfg_path))
    assert cfg.qkv_bias


def test_bias_shardings_filtered_correctly():
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    with_bias = init_params(TINY_QWEN, jax.random.key(0))
    s = param_shardings(mesh, TINY_QWEN, with_bias)
    assert "bq" in s["layers"]
    no_bias = init_params(dataclasses.replace(TINY_QWEN, qkv_bias=False), jax.random.key(0))
    s = param_shardings(mesh, TINY_QWEN, no_bias)
    assert "bq" not in s["layers"]
    # shardings are tree-compatible with the params
    jax.tree_util.tree_map(lambda a, b: None, no_bias, s)
