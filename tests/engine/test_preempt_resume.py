"""Preempt-and-resume under KV pressure, bounded admission with load
shedding, queued-deadline fail-fast, and the deterministic fault-injection
harness (testing.FAULTS).

The load-bearing guarantee: an overloaded engine must NEVER silently
truncate — a request the pool can't hold is preempted (tokens saved, pages
freed, requeued at the FRONT) and resumed via a prompt+partial prefill, so
every greedy response is byte-identical to an uncontended run.
"""

import dataclasses
import time

import pytest

import jax

from agentcontrolplane_tpu.engine.engine import (
    DeadlineExceededError,
    Engine,
    EngineOverloadedError,
    SamplingParams,
    _Slot,
)
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    # ACP_INVARIANTS posture for the whole fault suite: every
    # fault-injection run double-checks the engine's bookkeeping after
    # each dispatch cycle (engine/invariants.py)
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_slots=4,
        max_ctx=64,
        prefill_buckets=(32, 64),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- the tentpole guarantee --------------------------------------------------


def test_oversubscribed_pool_preempts_resumes_byte_identical():
    """Acceptance stress: concurrent requests oversubscribe a tiny KV pool
    (9 usable pages of size 8 -> ~2 concurrent 32-token sequences for 6
    requests). Every response must equal its uncontended run exactly, at
    least one preemption must be observed (request stat AND counter), and
    streamed tokens must arrive exactly once (no replay across resume)."""
    eng = make_engine(kv_pages=10)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        prompts = [ch * 20 for ch in "abcdef"]
        solo = {p: eng.generate(p, sp).tokens for p in prompts}
        before = counter("acp_engine_preemptions_total")

        streams = {p: [] for p in prompts}
        with eng.hold_admission():  # one burst, deterministic contention
            futs = [
                eng.submit(p, sp, on_tokens=streams[p].extend) for p in prompts
            ]
        results = dict(zip(prompts, (f.result(timeout=180) for f in futs)))

        for p, r in results.items():
            assert r.tokens == solo[p], f"contended output diverged for {p!r}"
            assert r.finish_reason in ("stop", "length")
            # pool pressure never shows up as a shortened generation
            assert len(r.tokens) == len(solo[p])
            assert streams[p] == [t for t in r.tokens], (
                "streamed tokens must arrive exactly once across resume"
            )
        assert any(r.preempt_count >= 1 for r in results.values())
        assert counter("acp_engine_preemptions_total") > before
        assert eng.preemptions >= 1
        assert eng.stats()["preemptions"] == eng.preemptions
        # all pages recycled once the burst drains
        deadline = time.monotonic() + 5
        while eng._allocator.free_count != eng.num_pages - 1:
            assert time.monotonic() < deadline, "leaked KV pages"
            time.sleep(0.05)
    finally:
        eng.stop()


def test_preempted_result_reports_honest_finish_reason():
    """A preempted-and-resumed greedy generation that runs to its token
    budget finishes 'length' with the FULL budget generated — 'length' may
    only ever mean max_tokens/ctx, never pool exhaustion."""
    eng = make_engine(kv_pages=10)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=10)
        futs = [eng.submit("y" * 24, sp) for _ in range(5)]
        for f in futs:
            r = f.result(timeout=180)
            if r.finish_reason == "length":
                assert len(r.tokens) == sp.max_tokens
    finally:
        eng.stop()


def test_victim_policy_fewest_tokens_then_most_recent():
    """Documented policy: fewest sampled tokens first; ties broken by most
    recently admitted."""
    eng = make_engine(kv_layout="slot")
    try:
        from concurrent.futures import Future

        from agentcontrolplane_tpu.engine.engine import _Request

        def fake_slot(n_tokens, seq):
            req = _Request(rid=f"r{seq}", prompt=[1], sampling=SamplingParams(), future=Future())
            return _Slot(request=req, generated=list(range(n_tokens)), admit_seq=seq)

        eng._slots = {0: fake_slot(5, 1), 1: fake_slot(2, 2), 2: fake_slot(2, 3)}
        # slots 1 and 2 tie on tokens; 2 was admitted later -> victim
        assert eng._pick_victim() == 2
        eng._slots.pop(2)
        assert eng._pick_victim() == 1
        eng._slots = {}
        assert eng._pick_victim() is None
    finally:
        eng._slots = {}
        eng.stop()


# -- bounded admission / load shedding ---------------------------------------


def test_queue_cap_sheds_instead_of_queueing_unboundedly():
    eng = make_engine(kv_layout="slot", max_queue=2)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=24)
        before = counter("acp_engine_shed_requests_total")
        with eng.hold_admission():
            kept = [eng.submit("x" * 8, sp) for _ in range(2)]
            shed = eng.submit("x" * 8, sp)
            with pytest.raises(EngineOverloadedError) as exc:
                shed.result(timeout=5)
            assert exc.value.retry_after_s >= 1.0
        assert counter("acp_engine_shed_requests_total") == before + 1
        for f in kept:  # the admitted work is unaffected by the shed
            assert f.result(timeout=120).finish_reason in ("stop", "length")
        assert eng.stats()["max_queue"] == 2
    finally:
        eng.stop()


def test_deadline_expired_in_queue_fails_fast_without_prefill():
    eng = make_engine(kv_layout="slot")
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        with eng.hold_admission():
            fut = eng.submit("z" * 8, sp, timeout_s=0.15)
            deadline = time.monotonic() + 10
            while not fut.done():
                assert time.monotonic() < deadline
                time.sleep(0.02)
            with pytest.raises(DeadlineExceededError, match="never admitted"):
                fut.result(timeout=0)
            # fail-fast means no slot was ever taken: admission never fired
            assert not fut.admitted.done()
    finally:
        eng.stop()


def test_no_deadline_means_no_expiry():
    eng = make_engine(kv_layout="slot")
    try:
        r = eng.generate("hello", SamplingParams(temperature=0.0, max_tokens=4))
        assert r.finish_reason in ("stop", "length")
        assert r.preempt_count == 0
    finally:
        eng.stop()


# -- fault injection (testing.FAULTS) ----------------------------------------


def test_fault_force_preempt_resumes_identically_slot_mode():
    """Forced preemption at a decode step N: works in BOTH kv layouts (the
    preempt/resume machinery is layout-independent) and the resumed greedy
    output is byte-identical."""
    eng = make_engine(kv_layout="slot")
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        baseline = eng.generate("preempt me", sp)
        assert baseline.preempt_count == 0
        FAULTS.arm("engine.force_preempt", after_steps=4)
        r = eng.generate("preempt me", sp)
        assert r.preempt_count == 1
        assert r.tokens == baseline.tokens
        assert not FAULTS.armed("engine.force_preempt")  # consumed
    finally:
        eng.stop()


def test_fault_page_pressure_shrinks_pool_midserve():
    """Injected pool pressure (pages held out of the allocator) must force
    preemption under concurrency while every response stays exact.
    Dedup off: the three identical prompts would otherwise SHARE their
    prompt pages (the PR 11 capacity multiplier) and fit the shrunken
    pool without the preemption this test exists to exercise."""
    eng = make_engine(kv_pages=17, prefix_dedup=False)  # 16 usable
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        solo = eng.generate("m" * 20, sp).tokens
        FAULTS.arm("engine.page_pressure", pages=8)  # halve the pool
        before = eng.preemptions
        with eng.hold_admission():
            futs = [eng.submit("m" * 20, sp) for _ in range(3)]
        for f in futs:
            assert f.result(timeout=180).tokens == solo
        assert eng.preemptions > before
        FAULTS.disarm("engine.page_pressure")
        # next block under an active slot releases the held pages
        eng.generate("m" * 20, sp)
        deadline = time.monotonic() + 5
        while eng._allocator.free_count != eng.num_pages - 1:
            assert time.monotonic() < deadline, "held pages not released"
            time.sleep(0.05)
    finally:
        eng.stop()


def test_fault_queue_full_sheds_one_submission():
    eng = make_engine(kv_layout="slot")
    try:
        FAULTS.arm("engine.queue_full")
        with pytest.raises(EngineOverloadedError):
            eng.submit("q", SamplingParams(max_tokens=2)).result(timeout=5)
        # one-shot: the next submission proceeds normally
        assert eng.generate("q", SamplingParams(temperature=0.0, max_tokens=2))
    finally:
        eng.stop()


def test_fault_engine_crash_recovers_via_ensure_running():
    eng = make_engine(kv_layout="slot")
    try:
        before = counter("acp_engine_crashes_total")
        FAULTS.arm("engine.crash")
        with pytest.raises(RuntimeError, match="engine crashed"):
            eng.submit("c" * 8, SamplingParams(max_tokens=4)).result(timeout=30)
        assert counter("acp_engine_crashes_total") == before + 1
        assert eng.ensure_running()
        r = eng.generate("c" * 8, SamplingParams(temperature=0.0, max_tokens=4))
        assert r.finish_reason in ("stop", "length")
    finally:
        eng.stop()
