"""Tokenizer, chat template, and tool-call parsing tests."""

from agentcontrolplane_tpu.api.resources import Message, MessageToolCall, ToolCallFunction
from agentcontrolplane_tpu.engine.tokenizer import (
    BOT,
    EOT,
    ByteTokenizer,
    render_prompt,
)
from agentcontrolplane_tpu.engine.toolparse import parse_tool_calls, to_message
from agentcontrolplane_tpu.llmclient.base import Tool, ToolFunction


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = f"{BOT}hello wörld{EOT}"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert ids[0] == 256  # BOT special, single token
    assert tok.stop_tokens


def test_render_prompt_basic():
    msgs = [
        Message(role="system", content="be brief"),
        Message(role="user", content="hi"),
    ]
    prompt = render_prompt(msgs, [])
    assert prompt.startswith(BOT)
    assert "be brief" in prompt
    assert prompt.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")


def test_render_prompt_injects_tools_and_serializes_calls():
    tools = [
        Tool(function=ToolFunction(name="web__fetch", description="fetch a url"))
    ]
    msgs = [
        Message(role="system", content="sys"),
        Message(role="user", content="get example.com"),
        Message(
            role="assistant",
            content="",
            tool_calls=[
                MessageToolCall(
                    id="call_1",
                    function=ToolCallFunction(
                        name="web__fetch", arguments='{"url": "https://example.com"}'
                    ),
                )
            ],
        ),
        Message(role="tool", content="<html></html>", tool_call_id="call_1"),
    ]
    prompt = render_prompt(msgs, tools)
    assert "web__fetch" in prompt  # schema in system prompt
    assert '"name": "web__fetch"' in prompt  # serialized call turn
    assert "<|start_header_id|>ipython<|end_header_id|>" in prompt  # tool result turn


def test_parse_whole_text_json():
    calls = parse_tool_calls('{"name": "web__fetch", "arguments": {"url": "x"}}')
    assert len(calls) == 1
    assert calls[0].function.name == "web__fetch"
    assert calls[0].function.arguments == '{"url": "x"}'


def test_parse_with_preamble_and_fences():
    text = 'Sure! I will fetch it:\n```json\n{"name": "web__fetch", "arguments": {"url": "x"}}\n```'
    calls = parse_tool_calls(text)
    assert len(calls) == 1
    text2 = 'Let me call {"name": "a__b", "arguments": {}} now'
    assert parse_tool_calls(text2)[0].function.name == "a__b"


def test_parse_arguments_as_string():
    calls = parse_tool_calls('{"name": "t__x", "arguments": "{\\"k\\": 1}"}')
    assert calls[0].function.arguments == '{"k": 1}'


def test_plain_text_is_not_a_tool_call():
    assert parse_tool_calls("the answer is 42") == []
    msg = to_message("the answer is 42")
    assert msg.content == "the answer is 42" and not msg.tool_calls


def test_unknown_tool_names_fall_back_to_content():
    msg = to_message(
        '{"name": "hallucinated__tool", "arguments": {}}', allowed_tools={"web__fetch"}
    )
    assert not msg.tool_calls  # hallucinated name doesn't break the state machine
    assert "hallucinated__tool" in msg.content


def test_tool_calls_beat_content():
    msg = to_message(
        'Here you go: {"name": "web__fetch", "arguments": {"url": "x"}}',
        allowed_tools={"web__fetch"},
    )
    assert msg.tool_calls and msg.content == ""
