"""int8 Pallas page walk (interpreter mode) vs the XLA dequant reference.

The kernel DMAs int8 pages plus their f32 scale rows and dequantizes in
VMEM with the exact ``kv_dequantize`` formula — so against the reference
(which dequantizes after the per-slot gather) the two paths compute the
same f32 math and the pin is the usual 1e-5, not a loose quantization
tolerance. Covers both entry forms, both sharded wrappers, scale-row
alignment edges (mid-page seq_lens, exact page boundaries, single-token
rows) and TRASH_PAGE / tail-row masking with poisoned scales.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.ops.paged import (
    TRASH_PAGE,
    paged_decode_attention_reference,
    paged_decode_attention_reference_cache_plus_new,
)
from agentcontrolplane_tpu.ops.pallas.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_cache_plus_new,
    paged_decode_attention_cache_plus_new_sharded,
    paged_decode_attention_sharded,
)
from agentcontrolplane_tpu.ops.quant import kv_quantize

from .test_paged import _setup


def _quantize_pages(k_pages, v_pages):
    """Per-row-per-head int8 pages + f32 scale twins (the allocator's
    storage layout: scales are pages-shaped, indexed by the same ids)."""
    kq, ks = kv_quantize(k_pages)
    vq, vs = kv_quantize(v_pages)
    return kq, vq, ks, vs


def _setup_int8(**kw):
    q, k_pages, v_pages, tables, seq_lens, _ = _setup(**kw)
    kq, vq, ks, vs = _quantize_pages(k_pages, v_pages)
    return q, kq, vq, ks, vs, tables, seq_lens


def test_int8_walk_matches_reference_interpret():
    q, kq, vq, ks, vs, tables, seq_lens = _setup_int8()
    ref = paged_decode_attention_reference(
        q, kq, vq, tables, seq_lens, k_scales=ks, v_scales=vs
    )
    out = paged_decode_attention(
        q, kq, vq, tables, seq_lens, interpret=True, k_scales=ks, v_scales=vs
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_int8_walk_gqa_and_bigger_shapes():
    q, kq, vq, ks, vs, tables, seq_lens = _setup_int8(
        seed=1, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    ref = paged_decode_attention_reference(
        q, kq, vq, tables, seq_lens, k_scales=ks, v_scales=vs
    )
    out = paged_decode_attention(
        q, kq, vq, tables, seq_lens, interpret=True, k_scales=ks, v_scales=vs
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_int8_cache_plus_new_matches_reference_interpret():
    """The serving hot-path form: int8 pages + a full-precision new token
    (not yet written, so no scale applies to the self term)."""
    for seed, kw in ((3, {}), (4, dict(S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16))):
        q, kq, vq, ks, vs, tables, seq_lens = _setup_int8(seed=seed, **kw)
        rng = np.random.default_rng(seed + 20)
        S, Hkv, d = q.shape[0], kq.shape[2], kq.shape[3]
        k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
        ref = paged_decode_attention_reference_cache_plus_new(
            q, kq, vq, tables, seq_lens, k_new, v_new, k_scales=ks, v_scales=vs
        )
        out = paged_decode_attention_cache_plus_new(
            q, kq, vq, tables, seq_lens, k_new, v_new, interpret=True,
            k_scales=ks, v_scales=vs,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )


def test_int8_walk_scale_row_alignment_edges():
    """Scale rows are [num_pages, P, H_kv] — NOT lane-padded — so the edge
    cases are sequence lengths that end mid-page, exactly on a page
    boundary, and a single-token row (the first fetch is also the last)."""
    base = _setup(seed=7, S=3, H=4, Hkv=2, d=8, P=4, max_pages=6, num_pages=32)
    q, k_pages, v_pages, tables, _, _ = base
    kq, vq, ks, vs = _quantize_pages(k_pages, v_pages)
    for lens in ([8, 4, 16], [1, 4, 17], [9, 1, 12], [4, 3, 1]):
        seq_lens = jnp.asarray(lens, dtype=jnp.int32)
        ref = paged_decode_attention_reference(
            q, kq, vq, tables, seq_lens, k_scales=ks, v_scales=vs
        )
        out = paged_decode_attention(
            q, kq, vq, tables, seq_lens, interpret=True, k_scales=ks, v_scales=vs
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5,
            err_msg=f"seq_lens={lens}",
        )


def test_int8_walk_masks_trash_page_and_poisoned_tail_scales():
    """Garbage in the masked region must not reach the output: poison the
    TRASH_PAGE and every row past each slot's seq_len (values AND scales)
    with large finite junk, and pin the result against the reference over
    the CLEAN pages — if the kernel read a poisoned scale row the outputs
    would diverge wildly, not within 1e-5."""
    q, kq, vq, ks, vs, tables, seq_lens = _setup_int8(seed=8)
    clean = paged_decode_attention_reference(
        q, kq, vq, tables, seq_lens, k_scales=ks, v_scales=vs
    )
    P = kq.shape[1]
    kq_p, vq_p = kq, vq
    ks_p = ks.at[TRASH_PAGE].set(1e30)
    vs_p = vs.at[TRASH_PAGE].set(1e30)
    kq_p = kq_p.at[TRASH_PAGE].set(127)
    vq_p = vq_p.at[TRASH_PAGE].set(127)
    for s in range(q.shape[0]):
        ln = int(seq_lens[s])
        last = (ln - 1) // P  # last walked page; poison its tail rows
        page = int(tables[s, last])
        off = ln - last * P
        if off < P:
            ks_p = ks_p.at[page, off:].set(1e30)
            vs_p = vs_p.at[page, off:].set(1e30)
            kq_p = kq_p.at[page, off:].set(127)
            vq_p = vq_p.at[page, off:].set(127)
    out = paged_decode_attention(
        q, kq_p, vq_p, tables, seq_lens, interpret=True,
        k_scales=ks_p, v_scales=vs_p,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(clean), rtol=1e-5, atol=1e-5
    )


def test_int8_walk_sharded_tp2_interpret():
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    q, kq, vq, ks, vs, tables, seq_lens = _setup_int8(
        seed=2, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    ref = paged_decode_attention_reference(
        q, kq, vq, tables, seq_lens, k_scales=ks, v_scales=vs
    )
    out = paged_decode_attention_sharded(
        mesh, q, kq, vq, tables, seq_lens, interpret=True,
        k_scales=ks, v_scales=vs,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_int8_cache_plus_new_sharded_tp_and_sp_interpret():
    """All sharded int8 forms: tp-only (shard_map over head-sharded pages
    and scale twins) and sp>1 (context-parallel slices with the cross-rank
    (acc, m, l) merge; scales shard with the pages' row axis)."""
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    q, kq, vq, ks, vs, tables, seq_lens = _setup_int8(
        seed=6, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    rng = np.random.default_rng(26)
    S, Hkv, d = q.shape[0], kq.shape[2], kq.shape[3]
    k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    ref = paged_decode_attention_reference_cache_plus_new(
        q, kq, vq, tables, seq_lens, k_new, v_new, k_scales=ks, v_scales=vs
    )
    for axes in ({"tp": 2}, {"sp": 4, "tp": 2}, {"sp": 2, "tp": 1}):
        n = axes.get("sp", 1) * axes.get("tp", 1)
        if len(jax.devices()) < n:
            pytest.skip(f"needs {n} devices")
        mesh = make_mesh(axes, devices=jax.devices()[:n])
        out = paged_decode_attention_cache_plus_new_sharded(
            mesh, q, kq, vq, tables, seq_lens, k_new, v_new, interpret=True,
            k_scales=ks, v_scales=vs,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5,
            err_msg=str(axes),
        )
