"""Gemma-2 family: exact logits vs transformers' Gemma2ForCausalLM.

Architecture deltas over Gemma-1: four-norm blocks (post-attention and
post-feedforward norms apply to the sublayer OUTPUT before the residual
add — HF reuses the name post_attention_layernorm with different
semantics than llama), tanh soft-capping on attention and final logits,
query_pre_attn_scalar replacing head_dim in the attention scale, GQA,
and alternating local/global layers. Tests run at T <= sliding_window,
where local attention == full causal (the engine enforces the same bound
for serving).
"""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.engine.weights import config_from_hf, params_from_state_dict
from agentcontrolplane_tpu.models.llama import PRESETS, forward

TINY_GEMMA2 = dict(
    vocab_size=256,
    hidden_size=64,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,  # GQA like gemma-2-2b
    head_dim=32,
    intermediate_size=128,
    rms_norm_eps=1e-6,
    rope_theta=10000.0,
    max_position_embeddings=128,
    hidden_activation="gelu_pytorch_tanh",
    attn_logit_softcapping=50.0,
    final_logit_softcapping=30.0,
    query_pre_attn_scalar=16,  # != head_dim (32): exercises the q scale
    sliding_window=128,  # >= test T: local == global (the serving bound)
)


@pytest.fixture(scope="module")
def gemma2_model_and_params(tmp_path_factory):
    torch = pytest.importorskip("torch")
    from transformers import Gemma2Config, Gemma2ForCausalLM

    hf_config = Gemma2Config(**TINY_GEMMA2, attn_implementation="eager")
    torch.manual_seed(0)
    model = Gemma2ForCausalLM(hf_config).eval()

    path = tmp_path_factory.mktemp("gemma2") / "config.json"
    cfg_doc = dict(TINY_GEMMA2)
    cfg_doc["model_type"] = "gemma2"
    path.write_text(json.dumps(cfg_doc))
    config = config_from_hf(str(path))
    assert config.post_norms and config.attn_logit_softcap == 50.0
    assert config.final_logit_softcap == 30.0
    assert config.query_pre_attn_scalar == 16.0
    assert config.sliding_window == 128
    config = dataclasses.replace(config, dtype=jnp.float32)
    params = params_from_state_dict(model.state_dict(), config)
    return model, params, config


def test_gemma2_logits_match_hf(gemma2_model_and_params):
    torch = pytest.importorskip("torch")
    model, params, config = gemma2_model_and_params
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, TINY_GEMMA2["vocab_size"], (2, 24))
    with torch.no_grad():
        ref = model(torch.asarray(tokens)).logits.float().numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, dtype=jnp.int32), config))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_gemma2_softcaps_change_the_function(gemma2_model_and_params):
    """Guard against the caps silently not being applied on either side.
    Random-init logits are tiny (tanh ~ identity there), so inflate the
    embedding (tied lm_head) to push logits well past the cap."""
    _, params, config = gemma2_model_and_params
    big = dict(params)
    big["embed"] = params["embed"] * 40.0
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(1, 256, (1, 16)), dtype=jnp.int32)
    capped = np.asarray(forward(big, tokens, config))
    uncapped = np.asarray(
        forward(
            big, tokens,
            dataclasses.replace(config, attn_logit_softcap=0.0, final_logit_softcap=0.0),
        )
    )
    assert np.max(np.abs(uncapped)) > 30.0, "test setup must exceed the cap"
    assert np.max(np.abs(capped)) <= 30.0 + 1e-3  # bounded by construction
    assert np.max(np.abs(capped - uncapped)) > 1.0


def test_gemma2_serves_in_engine(gemma2_model_and_params):
    """The whole serving path (prefill + continuation + decode) with the
    gemma-2 block, greedy tokens matching HF's generate."""
    torch = pytest.importorskip("torch")
    model, params, config = gemma2_model_and_params

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    prompt = [5, 9, 17, 33, 2]
    with torch.no_grad():
        hf_tokens = model.generate(
            torch.asarray([prompt]), max_new_tokens=6, do_sample=False,
        )[0, len(prompt):].tolist()

    engine = Engine(
        config=config, params=params, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=64, prefill_buckets=(32, 64),
        decode_block_size=4, seed=0,
    )
    engine.start()
    try:
        result = engine.generate(list(prompt), SamplingParams(temperature=0.0, max_tokens=6))
        assert result.tokens == hf_tokens, (result.tokens, hf_tokens)
    finally:
        engine.stop()


def test_gemma2_engine_refuses_unsupported_modes():
    from agentcontrolplane_tpu.engine.engine import Engine
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"tp": 1}, devices=jax.devices()[:1])
    cfg = dataclasses.replace(
        PRESETS["tiny"], attn_logit_softcap=50.0, post_norms=True,
        sliding_window=32, dtype=jnp.float32,
    )
    with pytest.raises(ValueError, match="slot"):
        Engine(config=dataclasses.replace(cfg, post_norms=False),
               tokenizer=ByteTokenizer(), mesh=mesh, max_slots=2, max_ctx=32,
               kv_layout="paged")
    with pytest.raises(ValueError, match="sliding window"):
        Engine(config=cfg, tokenizer=ByteTokenizer(), mesh=mesh,
               max_slots=2, max_ctx=64)


def test_gemma2_presets_shapes():
    for name in ("gemma2-2b", "gemma2-9b"):
        c = PRESETS[name]
        assert c.post_norms and c.attn_logit_softcap == 50.0
        assert c.final_logit_softcap == 30.0 and c.sliding_window == 4096
        assert c.head_dim == 256 and c.query_pre_attn_scalar == 256.0
