"""Weight-only int8: numerics, model-level fidelity, and engine serving."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS, forward, init_params
from agentcontrolplane_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    matmul,
    quantize,
    quantize_params,
)
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TINY = PRESETS["tiny"]


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 128)), dtype=jnp.float32)
    qt = quantize(w)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (1, 128)
    err = np.abs(np.asarray(dequantize(qt, jnp.float32)) - np.asarray(w))
    # symmetric int8: max error is scale/2 per channel
    assert err.max() <= float(np.asarray(qt.scale).max()) * 0.51


def test_quantize_all_zero_channel_takes_scale_floor():
    """Division-by-zero guard: an all-zero output channel has absmax 0 —
    the scale clamps to SCALE_FLOOR so the channel quantizes to zeros and
    dequantizes to EXACT zeros (finite everywhere, no NaN poisoning the
    whole matmul)."""
    from agentcontrolplane_tpu.ops.quant import SCALE_FLOOR

    rng = np.random.default_rng(3)
    w = rng.normal(size=(16, 8)).astype(np.float32)
    w[:, 2] = 0.0  # one dead channel
    qt = quantize(jnp.asarray(w))
    scales = np.asarray(qt.scale)[0]
    assert scales[2] == SCALE_FLOOR and np.all(np.isfinite(scales))
    deq = np.asarray(dequantize(qt, jnp.float32))
    assert np.all(np.isfinite(deq))
    assert np.all(deq[:, 2] == 0.0)
    # the dead channel contributes exact zeros through the fused matmul too
    out = np.asarray(matmul(jnp.ones((1, 16), jnp.float32), qt))
    assert np.all(np.isfinite(out)) and out[0, 2] == 0.0


def test_matmul_stays_fused_no_dequantized_operand():
    """The fused form ``(x @ q) * scale``: the compiled HLO must contain
    no weight-shaped MULTIPLY — the scale is applied to the [rows, out]
    RESULT, never to a materialized [in, out] dequantized matrix (the
    int8 operand feeds the dot through a bare convert, which TPU folds
    into the MXU operand load)."""
    rng = np.random.default_rng(4)
    w = quantize(jnp.asarray(rng.normal(size=(256, 64)), dtype=jnp.float32))
    x = jnp.asarray(rng.normal(size=(2, 256)), dtype=jnp.float32)
    hlo = jax.jit(matmul).lower(x, w).compile().as_text()
    weight_shaped_multiplies = [
        line for line in hlo.splitlines()
        if "multiply" in line and "[256,64]" in line
    ]
    assert not weight_shaped_multiplies, weight_shaped_multiplies


def test_matmul_quant_close_to_dense():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 64)), dtype=jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 32)), dtype=jnp.float32)
    dense = x @ w
    quant = matmul(x, quantize(w))
    rel = np.linalg.norm(np.asarray(quant - dense)) / np.linalg.norm(np.asarray(dense))
    assert rel < 0.01


def test_forward_with_quantized_params_high_fidelity():
    params = init_params(TINY, jax.random.key(0))
    qparams = quantize_params(params)
    assert isinstance(qparams["layers"]["wq"], QuantizedTensor)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, TINY.vocab_size, size=(1, 12)),
        dtype=jnp.int32,
    )
    dense = np.asarray(forward(params, tokens, TINY))
    quant = np.asarray(forward(qparams, tokens, TINY))
    # logits stay highly correlated and the argmax rarely moves
    corr = np.corrcoef(dense.ravel(), quant.ravel())[0, 1]
    assert corr > 0.999
    agree = (dense.argmax(-1) == quant.argmax(-1)).mean()
    assert agree >= 0.9


def test_engine_serves_int8():
    cfg = dataclasses.replace(TINY, vocab_size=512, n_kv_heads=2)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    eng = Engine(
        config=cfg,
        tokenizer=ByteTokenizer(),
        mesh=mesh,
        max_slots=2,
        max_ctx=64,
        prefill_buckets=(32, 64),
        quantize="int8",
    )
    assert isinstance(eng.params["layers"]["w1"], QuantizedTensor)
    eng.start()
    try:
        r = eng.generate("hello int8", SamplingParams(temperature=0.0, max_tokens=6))
        assert r.finish_reason in ("stop", "length")
        r2 = eng.generate("hello int8", SamplingParams(temperature=0.0, max_tokens=6))
        assert r.tokens == r2.tokens  # deterministic
    finally:
        eng.stop()


def test_engine_tp1_int8_host_side_random_init():
    """tp=1 + quantize=int8 + no params takes the host-side quantized init
    (the device path would peak at the full bf16 model — 16GB for 8B): the
    quantizable leaves arrive as QuantizedTensor and the engine serves."""
    cfg = dataclasses.replace(TINY, vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg,
        tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 1}, devices=jax.devices()[:1]),
        max_slots=2,
        max_ctx=64,
        prefill_buckets=(32, 64),
        quantize="int8",
    )
    assert isinstance(eng.params["layers"]["w1"], QuantizedTensor)
    assert eng.params["layers"]["w1"].q.dtype == jnp.int8
    eng.start()
    try:
        r = eng.generate("hello int8", SamplingParams(temperature=0.0, max_tokens=6))
        assert r.finish_reason in ("stop", "length")
    finally:
        eng.stop()


def test_engine_rejects_unknown_quantization():
    with pytest.raises(ValueError, match="unsupported quantization"):
        Engine(config=TINY, quantize="fp4", mesh=make_mesh({"tp": 1}, devices=jax.devices()[:1]))


def test_load_time_quantization_from_state_dict():
    """HF state dict -> int8 params without a device bf16 copy."""
    import torch
    from transformers import LlamaConfig as HFConfig, LlamaForCausalLM

    from agentcontrolplane_tpu.engine.weights import params_from_state_dict

    hf_config = HFConfig(
        vocab_size=TINY.vocab_size, hidden_size=TINY.dim,
        num_hidden_layers=TINY.n_layers, num_attention_heads=TINY.n_heads,
        num_key_value_heads=TINY.n_kv_heads, intermediate_size=TINY.ffn_dim,
        rms_norm_eps=TINY.norm_eps, rope_theta=TINY.rope_theta,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(hf_config).eval()
    dense = params_from_state_dict(model.state_dict(), TINY)
    quant = params_from_state_dict(model.state_dict(), TINY, quantize="int8")
    assert isinstance(quant["layers"]["wq"], QuantizedTensor)
    assert quant["layers"]["wq"].q.dtype == jnp.int8
    assert quant["layers"]["wq"].scale.dtype == jnp.float32
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, TINY.vocab_size, size=(1, 10)),
        dtype=jnp.int32,
    )
    a = np.asarray(forward(dense, tokens, TINY))
    b = np.asarray(forward(quant, tokens, TINY))
    assert np.corrcoef(a.ravel(), b.ravel())[0, 1] > 0.999


def test_random_quantized_init_matches_init_params_schema():
    """The host-side int8 random init (the 8B-on-one-chip bench path) must
    track init_params' layout exactly — its schema is DERIVED via
    eval_shape, so any new key/shape in init_params flows through; this
    guards the value policy and the quantized-leaf placement."""
    from agentcontrolplane_tpu.engine.weights import random_quantized_init
    from agentcontrolplane_tpu.ops.quant import QUANTIZABLE

    is_qt = lambda x: isinstance(x, QuantizedTensor)
    for cfg in (
        TINY,
        dataclasses.replace(TINY, qkv_bias=True, tie_embeddings=True),
    ):
        dense = init_params(cfg, jax.random.key(0))
        quant = random_quantized_init(cfg, seed=0)
        dense_by_key = {
            jax.tree_util.keystr(p): leaf
            for p, leaf in jax.tree_util.tree_leaves_with_path(dense)
        }
        quant_by_key = {
            jax.tree_util.keystr(p): leaf
            for p, leaf in jax.tree_util.tree_leaves_with_path(quant, is_leaf=is_qt)
        }
        assert quant_by_key.keys() == dense_by_key.keys()
        for ks, leaf in quant_by_key.items():
            name = ks.rsplit("['", 1)[-1].rstrip("']")
            if is_qt(leaf):
                assert name in QUANTIZABLE and ks.startswith("['layers']")
                assert leaf.q.dtype == jnp.int8
                assert leaf.q.shape == dense_by_key[ks].shape
            else:
                assert not (ks.startswith("['layers']") and name in QUANTIZABLE)
                assert leaf.shape == dense_by_key[ks].shape, ks
