"""Overlapped tool execution end to end: Task controller + TPU engine.

Lives under tests/engine (not tests/controllers) deliberately: it builds
real Engines, and on this jax build an engine's jitted programs poison
later TRAINER compiles in the same process (the known CPU donation bug
class, see the _put upload guard) — so like every other engine-building
test it must run AFTER the train-path tests (lora/moe/parallel_train),
which pytest's alphabetical order within this directory provides.

The tentpole contract at the control-plane level: with overlap ON the
ToolCall CR is created the moment the streamed call's arguments close
(acp_task_early_toolcalls_total) and the engine slot parks after the turn;
with overlap OFF everything happens after the full completion — and the
JOINED CONVERSATION STATE is identical either way (modulo generated call
ids, which are random in both modes).
"""

import asyncio
import dataclasses

import jax
import pytest

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    LLM,
    BaseConfig,
    LLMSpec,
    MCPTool,
    TPUProviderConfig,
    TASK_PHASE_FAILED,
)
from agentcontrolplane_tpu.engine.engine import Engine
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.metrics import REGISTRY
from agentcontrolplane_tpu.operator import Operator, OperatorOptions
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import (
    FAULTS,
    make_agent,
    make_mcpserver,
    make_task,
    setup_with_status,
)

CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=512, n_kv_heads=2)


class FakeMCPManager:
    def __init__(self):
        self.calls = []

    def get_tools(self, name):
        if name != "svc":
            return []
        return [
            MCPTool(
                name="lookup",
                description="look something up",
                input_schema={"type": "object", "properties": {}},
            )
        ]

    async def call_tool(self, server, tool, args):
        self.calls.append((server, tool, args))
        return "lookup-result"


def make_engine():
    eng = Engine(
        config=CFG,
        tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=4,
        max_ctx=512,
        prefill_buckets=(64, 128, 256, 512),
        decode_block_size=4,
        kv_layout="slot",
    )
    eng.start()
    return eng


def counter(name: str) -> float:
    m = REGISTRY._metrics.get(name)
    return 0.0 if m is None else m.values.get((), 0.0)


def normalized_window(task):
    """Context window with the random call ids replaced positionally, so
    two runs compare on structure + content."""
    out = []
    for m in task.status.context_window:
        calls = [
            (tc.function.name, tc.function.arguments) for tc in (m.tool_calls or [])
        ]
        out.append((m.role, m.content, calls, bool(m.tool_call_id)))
    return out


async def drive_turn(overlap: bool, mid_turn=None):
    """Run one full tool-call turn (send -> fan-out -> execute -> join) and
    return (normalized window, fake manager, engine stats, task). The LLM
    forces a single parseable call via tool_choice=required, so a random-
    weights model produces a real ToolCall deterministically."""
    engine = make_engine()
    op = Operator(
        options=OperatorOptions(
            enable_rest=False, llm_probe=False,
            verify_channel_credentials=False, engine=engine,
        ),
    )
    op.task_reconciler.requeue_delay = 0.02
    op.toolcall_reconciler.poll_interval = 0.02
    fake = FakeMCPManager()
    op.task_reconciler.mcp_manager = fake
    op.toolcall_reconciler.mcp_manager = fake
    store = op.store
    try:
        setup_with_status(
            store,
            LLM(
                metadata=ObjectMeta(name="tpu-llm"),
                spec=LLMSpec(
                    provider="tpu",
                    parameters=BaseConfig(model="tiny", max_tokens=24, temperature=0.0),
                    tpu=TPUProviderConfig(
                        preset="tiny", overlap_tool_calls=overlap
                    ),
                    provider_config={"tool_choice": "required"},
                ),
            ),
            lambda o: (
                setattr(o.status, "ready", True),
                setattr(o.status, "status", "Ready"),
            ),
        )
        make_mcpserver(store, name="svc", tools=("lookup",))
        make_agent(store, name="agent", llm="tpu-llm", system="use tools",
                   mcp_servers=("svc",))
        await op.start()
        make_task(store, name="t1", agent="agent", user_message="look it up")

        deadline = asyncio.get_running_loop().time() + 120
        task = None
        while asyncio.get_running_loop().time() < deadline:
            task = store.try_get("Task", "t1", "default")
            if task is not None and task.status.phase == TASK_PHASE_FAILED:
                raise AssertionError(f"task failed: {task.status.error}")
            # one full turn joined: [system, user, assistant(calls), tool]
            if task is not None and task.status.message_count >= 4:
                break
            if mid_turn is not None:
                await mid_turn(engine, task)
            await asyncio.sleep(0.02)
        assert task is not None and task.status.message_count >= 4, (
            task and task.status.phase
        )
        stats = engine.stats()
        from agentcontrolplane_tpu.api.resources import ToolCall

        crs = [
            tc for tc in store.list("ToolCall", "default")
            if isinstance(tc, ToolCall)
        ]
        return normalized_window(task), fake, stats, (task, crs)
    finally:
        await op.stop()
        engine.stop()


async def test_overlap_on_off_identical_joined_state():
    before = counter("acp_task_early_toolcalls_total")
    win_on, fake_on, stats_on, _ = await drive_turn(overlap=True)
    after = counter("acp_task_early_toolcalls_total")
    win_off, fake_off, stats_off, _ = await drive_turn(overlap=False)

    # the load-bearing contract: identical joined conversation state (the
    # constrained completion's argument JSON is arbitrary with random
    # weights but greedily deterministic — both modes must agree exactly)
    assert win_on == win_off
    assert fake_on.calls == fake_off.calls
    assert [c[:2] for c in fake_on.calls] == [("svc", "lookup")]
    # overlap actually took the early path and parked the finished slot
    assert after - before >= 1
    assert stats_on["tool_overlap"]["parks"] >= 1
    assert stats_on["tool_overlap"]["early_calls"] >= 1
    # plain mode took neither
    assert stats_off["tool_overlap"]["parks"] == 0
    assert stats_off["tool_overlap"]["early_calls"] == 0


async def test_stress_early_dispatch_slow_tool_force_preempt_on_parked_slot():
    """Satellite stress: the streamed call dispatches early, the tool is
    slow (fault tool.slow), and while the slot sits parked waiting out the
    tool a forced preemption lands on it — the parked slot absorbs the
    fault (voluntary release), the join still completes, and the joined
    state matches an unstressed run."""
    # the slow tool holds the join open long enough for the filler's cold
    # decode-width compile to finish INSIDE the parked window (turn 2 must
    # not start and adopt the parked slot before the fault fires)
    FAULTS.arm("tool.slow", times=1, seconds=6.0)
    fired = {"done": False, "released_in_window": False}

    async def mid_turn(engine, task):
        # once the turn parked (generation done, slow tool still running),
        # force a preemption via an unrelated engine request — the victim
        # scan must pick the parked slot. json_only + an open forced
        # prefix guarantees the filler actually DECODES (grammar masks
        # stop tokens until the object closes), so the fault site in the
        # decode path is reached deterministically.
        if not fired["done"] and engine.stats()["parked_slots"] == 1:
            fired["done"] = True
            FAULTS.arm("engine.force_preempt", times=1)
            from agentcontrolplane_tpu.engine.engine import SamplingParams

            engine.submit(
                "unrelated filler work",
                SamplingParams(
                    temperature=0.0, max_tokens=24, json_only=True,
                    forced_prefix=tuple(
                        engine.tokenizer.encode('{"filler": ')
                    ),
                ),
            )
            deadline = asyncio.get_running_loop().time() + 60
            while (
                FAULTS.armed("engine.force_preempt")
                and asyncio.get_running_loop().time() < deadline
            ):
                await asyncio.sleep(0.05)
            fired["released_in_window"] = engine.stats()["parked_slots"] == 0

    try:
        win, fake, stats, _ = await drive_turn(overlap=True, mid_turn=mid_turn)
    finally:
        FAULTS.reset()
    assert fired["done"], "parked window never observed"
    assert fired["released_in_window"], "forced preemption missed the parked slot"
    assert stats["tool_overlap"]["park_releases"] >= 1
    assert [c[:2] for c in fake.calls] == [("svc", "lookup")]

    ref_win, _, _, _ = await drive_turn(overlap=False)
    assert win == ref_win


async def test_early_cr_is_adopted_by_fan_out():
    """The early-created CR must BE the turn's fan-out: its request_id
    label matches task.status.tool_call_request_id and its tool_call_id is
    the id recorded in the assistant message (no duplicate CRs)."""
    _, _, _, (task, crs) = await drive_turn(overlap=True)
    assistant = next(
        m for m in task.status.context_window if m.role == "assistant" and m.tool_calls
    )
    assert len(crs) == 1  # adopted, not duplicated
    cr = crs[0]
    rid = task.status.tool_call_request_id
    from agentcontrolplane_tpu.api.resources import LABEL_TOOL_CALL_REQUEST

    assert rid and cr.metadata.labels.get(LABEL_TOOL_CALL_REQUEST) == rid
    assert cr.spec.tool_call_id == assistant.tool_calls[0].id
    assert cr.spec.tool_ref.name == "svc__lookup"
