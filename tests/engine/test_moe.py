"""Mixture-of-Experts FFN (ops/moe.py) + the Mixtral-architecture family.

The GShard dispatch/combine formulation must match the exact per-token
reference whenever capacity doesn't bind; expert parallelism ('ep' mesh
axis) must be numerically transparent and must not all-gather the expert
weights.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.models.llama import PRESETS, forward, init_params
from agentcontrolplane_tpu.ops.moe import (
    expert_capacity,
    moe_ffn,
    moe_ffn_reference,
    route_topk,
)
from agentcontrolplane_tpu.parallel.mesh import make_mesh, param_shardings

MOE = PRESETS["moe-tiny"]


def _weights(seed=0, E=4, D=64, F=128):
    rng = np.random.default_rng(seed)
    mk = lambda *shape: jnp.asarray(
        rng.normal(size=shape) * 0.05, dtype=jnp.float32
    )
    return mk(D, E), mk(E, D, F), mk(E, D, F), mk(E, F, D)


def test_route_topk_renormalizes_over_selection():
    logits = jnp.asarray([[1.0, 3.0, 2.0, -1.0]])
    idx, w = route_topk(logits, 2)
    assert sorted(np.asarray(idx[0]).tolist()) == [1, 2]
    np.testing.assert_allclose(float(jnp.sum(w)), 1.0, rtol=1e-6)
    # softmax over the selected two logits only
    expect = np.exp([3.0, 2.0]) / np.exp([3.0, 2.0]).sum()
    np.testing.assert_allclose(np.sort(np.asarray(w[0]))[::-1], expect, rtol=1e-6)


def test_moe_ffn_matches_per_token_reference():
    router, w1, w3, w2 = _weights()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(13, 64)), dtype=jnp.float32)
    cap = expert_capacity(13, 4, 2, 8.0)  # generous: nothing drops
    out = moe_ffn(x, router, w1, w3, w2, experts_per_token=2, capacity=cap)
    ref = moe_ffn_reference(x, router, w1, w3, w2, experts_per_token=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_moe_capacity_overflow_drops_to_residual():
    """With capacity 1 per expert, overflowed (token, expert) choices must
    contribute ZERO (the residual carries the token) — never alias another
    expert's slot."""
    router, w1, w3, w2 = _weights(seed=2)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(9, 64)), dtype=jnp.float32)
    out = moe_ffn(x, router, w1, w3, w2, experts_per_token=2, capacity=2)
    # bounded: every output row is a convex-ish combination of expert FFNs
    # of x rows; a scatter aliasing bug produces garbage magnitudes
    assert np.isfinite(np.asarray(out)).all()
    full = moe_ffn(
        x, router, w1, w3, w2, experts_per_token=2,
        capacity=expert_capacity(9, 4, 2, 8.0),
    )
    # capacity-2 keeps the first-fitting choices; rows whose choices ALL fit
    # match the uncapped result exactly — verify at least one row does and
    # none exceed the uncapped magnitude wildly
    matches = np.isclose(np.asarray(out), np.asarray(full), rtol=1e-5, atol=1e-5)
    assert matches.all(axis=1).any()


def test_forward_moe_tiny_finite_and_deterministic():
    params = init_params(MOE, jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, MOE.vocab_size, size=(2, 16)),
        dtype=jnp.int32,
    )
    logits = forward(params, tokens, MOE)
    assert logits.shape == (2, 16, MOE.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    logits2 = forward(params, tokens, MOE)
    np.testing.assert_array_equal(np.asarray(logits), np.asarray(logits2))


def test_forward_moe_batch_independent_with_slack_capacity():
    """moe-tiny's capacity factor leaves no drops, so a row's logits must
    not depend on what else is in the batch (serving correctness: solo ==
    batched)."""
    params = init_params(MOE, jax.random.key(0))
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.integers(1, MOE.vocab_size, size=(1, 12)), dtype=jnp.int32)
    b = jnp.asarray(rng.integers(1, MOE.vocab_size, size=(1, 12)), dtype=jnp.int32)
    solo = forward(params, a, MOE)
    batched = forward(params, jnp.concatenate([a, b]), MOE)
    np.testing.assert_allclose(
        np.asarray(solo[0]), np.asarray(batched[0]), rtol=2e-4, atol=2e-4
    )


def test_expert_parallel_forward_matches_replicated_no_weight_allgather():
    """ep2 x tp2: expert-sharded forward == replicated forward, and the
    compiled HLO contains no expert-weight-sized all-gather (each rank
    computes only its own experts' batches)."""
    import re

    from jax.sharding import NamedSharding, PartitionSpec as P

    params = init_params(MOE, jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(1, MOE.vocab_size, size=(2, 16)),
        dtype=jnp.int32,
    )
    ref = jax.jit(lambda p, t: forward(p, t, MOE))(params, tokens)

    mesh = make_mesh({"ep": 2, "tp": 2}, devices=jax.devices()[:4])
    p_sh = param_shardings(mesh, MOE, params)
    rep = NamedSharding(mesh, P())
    fn = jax.jit(
        lambda p, t: forward(p, t, MOE),
        in_shardings=(p_sh, rep),
        out_shardings=rep,
    )
    params_ep = jax.device_put(params, p_sh)
    compiled = fn.lower(params_ep, tokens).compile()
    out = fn(params_ep, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-3, atol=2e-3)

    # an expert stack is [L, E, D, F]; one layer's experts = E*D*F elements.
    # Anything that size being all-gathered means GSPMD replicated the
    # expert weights instead of dispatching tokens to them.
    expert_elems = MOE.n_experts * MOE.dim * MOE.ffn_dim
    for line in compiled.as_text().splitlines():
        if "all-gather" not in line:
            continue
        dims = re.search(r"\[([0-9,]+)\]", line)
        assert dims is not None, line
        elems = int(np.prod([int(x) for x in dims.group(1).split(",")]))
        assert elems < expert_elems // 2, f"expert-sized all-gather: {line.strip()[:160]}"


def test_moe_serves_through_the_engine():
    """The MoE family drops into the serving engine unchanged (the MLP swap
    lives inside _attn_mlp): greedy generation, both KV layouts, identical
    tokens."""
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer

    cfg = dataclasses.replace(MOE, vocab_size=512)
    outs = {}
    for layout in ("slot", "paged"):
        eng = Engine(
            config=cfg,
            tokenizer=ByteTokenizer(),
            mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
            max_slots=2,
            max_ctx=64,
            prefill_buckets=(32, 64),
            decode_block_size=4,
            kv_layout=layout,
            page_size=8,
            seed=0,
        )
        eng.start()
        try:
            outs[layout] = eng.generate(
                "hello moe", SamplingParams(temperature=0.0, max_tokens=8)
            ).tokens
        finally:
            eng.stop()
    assert outs["slot"] == outs["paged"]
    assert len(outs["slot"]) >= 1


def test_mixtral_logits_match_hf():
    """Weight mapping + MoE forward pinned against HF transformers'
    MixtralForCausalLM on a tiny random checkpoint (the same exactness
    contract as the llama/qwen/gemma families)."""
    torch = pytest.importorskip("torch")
    from transformers import MixtralConfig, MixtralForCausalLM

    from agentcontrolplane_tpu.engine.weights import params_from_state_dict
    from agentcontrolplane_tpu.models.llama import LlamaConfig

    tiny = LlamaConfig(
        vocab_size=256,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=128,
        rope_theta=10000.0,
        n_experts=4,
        experts_per_token=2,
        expert_capacity_factor=8.0,  # no drops: HF routes without capacity
        dtype=jnp.float32,
    )
    hf_config = MixtralConfig(
        vocab_size=tiny.vocab_size,
        hidden_size=tiny.dim,
        num_hidden_layers=tiny.n_layers,
        num_attention_heads=tiny.n_heads,
        num_key_value_heads=tiny.n_kv_heads,
        intermediate_size=tiny.ffn_dim,
        num_local_experts=tiny.n_experts,
        num_experts_per_tok=tiny.experts_per_token,
        rms_norm_eps=tiny.norm_eps,
        rope_theta=tiny.rope_theta,
        max_position_embeddings=tiny.max_seq_len,
        tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = MixtralForCausalLM(hf_config).eval()
    params = params_from_state_dict(model.state_dict(), tiny)
    assert params["layers"]["w1"].shape == (2, 4, 64, 128)
    assert params["layers"]["router"].shape == (2, 64, 4)
    tokens = np.random.default_rng(0).integers(0, tiny.vocab_size, size=(2, 13))
    with __import__("torch").no_grad():
        hf_logits = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(forward(params, jnp.asarray(tokens, dtype=jnp.int32), tiny))
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)


def test_mixtral_config_from_hf(tmp_path):
    import json

    from agentcontrolplane_tpu.engine.weights import config_from_hf

    cfg = {
        "model_type": "mixtral",
        "vocab_size": 32000,
        "hidden_size": 4096,
        "num_hidden_layers": 32,
        "num_attention_heads": 32,
        "num_key_value_heads": 8,
        "intermediate_size": 14336,
        "num_local_experts": 8,
        "num_experts_per_tok": 2,
        "rope_theta": 1000000.0,
        "max_position_embeddings": 32768,
    }
    p = tmp_path / "config.json"
    p.write_text(json.dumps(cfg))
    c = config_from_hf(str(p))
    assert c.n_experts == 8 and c.experts_per_token == 2
    assert c.ffn_dim == 14336


def test_moe_train_step_over_dp_ep_mesh():
    """The trainer takes the MoE family unchanged: one dp2 x ep2 x tp2
    train step produces a finite loss that matches the unsharded step."""
    import optax

    from agentcontrolplane_tpu.train.trainer import Trainer

    cfg = dataclasses.replace(MOE, vocab_size=128)
    batch = np.random.default_rng(0).integers(1, cfg.vocab_size, size=(4, 16))

    def one_step(mesh_axes):
        mesh = make_mesh(mesh_axes, devices=jax.devices()[: int(np.prod(list(mesh_axes.values())))])
        tr = Trainer(config=cfg, mesh=mesh, optimizer=optax.adamw(1e-3))
        params, opt = tr.init(jax.random.key(0))
        tokens, mask = tr.shard_batch(batch)
        _, _, loss = tr.train_step(params, opt, tokens, mask)
        return float(loss)

    sharded = one_step({"dp": 2, "ep": 2, "tp": 2})
    base = one_step({"dp": 1, "tp": 1})
    assert np.isfinite(sharded)
    np.testing.assert_allclose(sharded, base, rtol=2e-3)


def test_moe_serves_on_expert_parallel_mesh():
    """Engine over serving_mesh(ep=2): greedy tokens identical to tp-only."""
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer

    cfg = dataclasses.replace(MOE, vocab_size=512)

    def run(mesh):
        eng = Engine(
            config=cfg, tokenizer=ByteTokenizer(), mesh=mesh,
            max_slots=2, max_ctx=64, prefill_buckets=(32, 64),
            decode_block_size=4, seed=0,
        )
        eng.start()
        try:
            return eng.generate(
                "expert parallel", SamplingParams(temperature=0.0, max_tokens=8)
            ).tokens
        finally:
            eng.stop()

    ref = run(make_mesh({"tp": 2}, devices=jax.devices()[:2]))
    ep = run(make_mesh({"ep": 2, "tp": 2}, devices=jax.devices()[:4]))
    assert ep == ref and len(ref) >= 1


def test_moe_int8_quantization():
    """Weight-only int8 applies per expert stack ([L, E, D, F] tensors;
    per-channel scales over the contraction dim) and moe_ffn dequantizes
    transparently — outputs close to bf16."""
    from agentcontrolplane_tpu.ops.quant import quantize

    router, w1, w3, w2 = _weights(seed=5)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(11, 64)), dtype=jnp.float32)
    cap = expert_capacity(11, 4, 2, 8.0)
    ref = moe_ffn(x, router, w1, w3, w2, experts_per_token=2, capacity=cap)
    out = moe_ffn(
        x, router, quantize(w1), quantize(w3), quantize(w2),
        experts_per_token=2, capacity=cap,
    )
    assert quantize(w1).q.shape == (4, 64, 128)
    assert quantize(w1).scale.shape == (4, 1, 128)  # per-channel over D
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=0.1, atol=0.05)


def test_moe_grouped_matches_reference_across_group_boundaries():
    """N > group_size splits tokens into fixed-capacity groups (the thing
    that keeps dispatch O(group) per token); with slack capacity the result
    must still match the exact per-token reference — including the padded
    final group, whose pad rows must consume no expert capacity."""
    router, w1, w3, w2 = _weights(seed=7)
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(size=(21, 64)), dtype=jnp.float32)
    ref = moe_ffn_reference(x, router, w1, w3, w2, experts_per_token=2)
    out = moe_ffn(
        x, router, w1, w3, w2, experts_per_token=2,
        capacity=expert_capacity(8, 4, 2, 8.0),  # per-group (G=8)
        group_size=8,  # 21 tokens -> groups of 8, 8, 5(+3 pad)
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
