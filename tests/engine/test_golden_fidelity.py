"""Golden-vector fidelity for the real-checkpoint serving path (VERDICT r2 #4).

``HFTokenizer`` + ``render_prompt`` are the only two steps between a real
Llama-3 checkpoint directory and the engine; these tests pin both against
independently generated goldens (HF ``transformers``' fast tokenizer and
``apply_chat_template`` with the official Llama-3 Jinja template) over a
Llama-3-structured tokenizer.json — same byte-level BPE pipeline, split
regex, ByteLevel alphabet, and special-token set as the real checkpoint
asset. See ``golden/build_goldens.py`` for how the assets are produced;
with a real downloaded tokenizer.json the code path is identical, so the
only untested step is the download itself.
"""

from __future__ import annotations

import json
import pathlib

import pytest

from agentcontrolplane_tpu.api.resources import Message
from agentcontrolplane_tpu.engine.tokenizer import HFTokenizer, render_prompt

GOLDEN = pathlib.Path(__file__).parent / "golden"


@pytest.fixture(scope="module")
def tok() -> HFTokenizer:
    return HFTokenizer(str(GOLDEN / "tokenizer.json"))


@pytest.fixture(scope="module")
def vectors() -> list[dict]:
    return json.loads((GOLDEN / "vectors.json").read_text())


@pytest.fixture(scope="module")
def chat_goldens() -> list[dict]:
    return json.loads((GOLDEN / "chat_goldens.json").read_text())


def test_encode_matches_transformers_golden_vectors(tok, vectors):
    for v in vectors:
        assert tok.encode(v["text"]) == v["ids"], f"encode mismatch: {v['text']!r}"


def test_decode_matches_transformers_golden_vectors(tok, vectors):
    for v in vectors:
        assert tok.decode(v["ids"]) == v["decoded"], f"decode mismatch: {v['text']!r}"


def test_round_trip_is_lossless_for_plain_text(tok, vectors):
    """Byte-level BPE must reconstruct every input exactly (no normalizer)."""
    for v in vectors:
        assert tok.decode(tok.encode(v["text"])) == v["decoded"]


def test_stop_tokens_are_the_llama3_terminators(tok):
    ids = {tok._tok.token_to_id(s) for s in ("<|eot_id|>", "<|end_of_text|>")}
    assert None not in ids
    assert tok.stop_tokens == ids


def test_token_bytes_inverts_the_bytelevel_alphabet(tok, vectors):
    """The grammar-constraint engine walks candidate tokens byte-by-byte;
    token_bytes must agree with what the tokenizer actually decodes."""
    for v in vectors:
        ids = v["ids"]
        specials = tok.stop_tokens | {
            tok._tok.token_to_id(s)
            for s in ("<|begin_of_text|>", "<|start_header_id|>",
                      "<|end_header_id|>", "<|python_tag|>")
        }
        if any(i in specials for i in ids):
            continue  # specials have no byte expansion (token_bytes -> None)
        blob = b"".join(tok.token_bytes(i) for i in ids)
        assert blob.decode("utf-8") == v["decoded"]


def test_specials_have_no_byte_expansion(tok):
    for s in ("<|begin_of_text|>", "<|eot_id|>", "<|end_of_text|>"):
        assert tok.token_bytes(tok._tok.token_to_id(s)) is None


def test_chat_template_matches_transformers_render(chat_goldens):
    """render_prompt == transformers.apply_chat_template (official Llama-3
    template: bos, header turns, trimmed content, generation prompt)."""
    for case in chat_goldens:
        messages = [Message(**m) for m in case["messages"]]
        assert render_prompt(messages, []) == case["rendered"]


def test_chat_template_tokenizes_to_transformers_ids(tok, chat_goldens):
    """End-to-end: our render + our tokenizer == transformers' tokenized
    chat — the exact token stream a real checkpoint would be served."""
    for case in chat_goldens:
        messages = [Message(**m) for m in case["messages"]]
        assert tok.encode(render_prompt(messages, [])) == case["ids"]


def test_goldens_regenerate_deterministically():
    """Guard the assets against silent drift: rebuilding from the checked-in
    builder must reproduce the checked-in vectors byte-for-byte."""
    import subprocess
    import sys
    import tempfile
    import shutil

    pytest.importorskip("transformers")  # builder-only dependency

    with tempfile.TemporaryDirectory() as td:
        dst = pathlib.Path(td) / "golden"
        shutil.copytree(GOLDEN, dst)
        # regenerate in the copy and compare the derived assets (the BPE
        # train is deterministic given the same corpus+trainer settings)
        build = dst / "build_goldens.py"
        out = subprocess.run(
            [sys.executable, str(build)], capture_output=True, text=True, timeout=300
        )
        assert out.returncode == 0, out.stderr[-2000:]
        for name in ("vectors.json", "chat_goldens.json"):
            assert (dst / name).read_text() == (GOLDEN / name).read_text(), (
                f"{name} drifted from its builder"
            )
