"""Overlapped tool execution, engine side: early tool-call events from the
decode stream, park-at-finish slots, adoption by the next turn, and the
byte-identity contract — overlap/park on vs off changes WHEN tool calls
become dispatchable, never what is generated.
"""

import dataclasses
import threading
import time

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2)

TWO_CALLS = '{"name": "t1", "arguments": {"x": 1}} {"name": "t2", "arguments": {}}'


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("park_max_s", 30.0)
    kw.setdefault("max_slots", 4)
    eng = Engine(
        config=CFG,
        tokenizer=TOK,
        mesh=mesh,
        max_ctx=256,
        prefill_buckets=(32, 64, 128),
        decode_block_size=4,
        kv_layout=kv_layout,
        page_size=8,
        **kw,
    )
    eng.start()
    return eng


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_early_events_fire_before_generation_ends(kv_layout):
    """Two tool calls closing before a ~40-token decode tail must be
    surfaced while the model is still generating: each event strictly
    precedes the future's resolution, in stream order, and the same list
    rides the future as ``early_tool_calls``."""
    eng = make_engine(kv_layout)
    try:
        events = []
        done_at = {}
        fut = eng.submit(
            "hello " * 8,
            SamplingParams(
                temperature=0.0, max_tokens=40,
                forced_prefix=tuple(TOK.encode(TWO_CALLS)),
            ),
            on_tool_call=lambda i, tc: events.append((i, tc.function.name, time.monotonic())),
            park=False,
        )
        res = fut.result(120)
        done_at["t"] = time.monotonic()
        assert [(i, n) for i, n, _ in events] == [(0, "t1"), (1, "t2")]
        assert all(t < done_at["t"] for _, _, t in events)
        assert [tc.function.name for _, tc in fut.early_tool_calls] == ["t1", "t2"]
        assert len(res.tokens) >= 40
        s = eng.stats()["tool_overlap"]
        assert s["early_calls"] == 2
        # the ordering above IS the contract; saved-seconds can round to
        # 0.0 when detok holdback defers both calls to the final flush
        # (order-dependent flake pre-existing since PR 12), so assert the
        # counter is present and sane rather than strictly positive
        assert s["overlap_saved_s"] >= 0
    finally:
        eng.stop()


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_overlap_park_two_turn_byte_identity(kv_layout):
    """The safety rail: a two-turn conversation with overlap + park on
    (turn 2 adopts the parked slot, suffix-only prefill) generates the
    exact token streams of a plain engine, in both KV layouts."""
    turn1 = "user question " * 4
    turn2 = turn1 + "assistant said things; tool results; next question"
    sp1 = SamplingParams(
        temperature=0.0, max_tokens=16, forced_prefix=tuple(TOK.encode(TWO_CALLS))
    )
    sp2 = SamplingParams(temperature=0.0, max_tokens=8)

    eng = make_engine(kv_layout)
    try:
        r1 = eng.submit(turn1, sp1, on_tool_call=lambda i, tc: None, park=True).result(120)
        assert eng.stats()["parked_slots"] == 1
        r2 = eng.submit(turn2, sp2).result(120)
        st = eng.stats()["tool_overlap"]
        assert st["parks"] == 1 and st["park_adoptions"] == 1
        assert eng.stats()["parked_slots"] == 0  # turn 2 didn't ask to park
    finally:
        eng.stop()

    ref = make_engine(kv_layout, park_max_s=0.0)
    try:
        p1 = ref.submit(turn1, sp1).result(120)
        p2 = ref.submit(turn2, sp2).result(120)
    finally:
        ref.stop()
    assert r1.tokens == p1.tokens and r1.text == p1.text
    assert r2.tokens == p2.tokens and r2.text == p2.text


def test_overlap_byte_identity_with_speculation_and_json_constraint():
    """Speculation on + grammar-forced tool call + overlap/park vs the
    plain spec-off engine: identical bytes, and the (decoded, not
    prefilled) closing brace still emits an early event — the spec path's
    multi-token commits feed the same stream seam."""
    envelope = '{"name": "fetch", "arguments": {'
    sp = SamplingParams(
        temperature=0.0, max_tokens=48, json_only=True,
        forced_prefix=tuple(TOK.encode(envelope)),
    )
    prompt = "fetch fetch fetch " * 6  # self-repetitive: lets the drafter engage

    eng = make_engine("paged", spec_len=8, spec_ngram=3)
    try:
        events = []
        r = eng.submit(
            prompt, sp, on_tool_call=lambda i, tc: events.append(tc), park=True
        ).result(180)
        assert [tc.function.name for tc in events] == ["fetch"]
        assert eng.stats()["tool_overlap"]["parks"] == 1
    finally:
        eng.stop()

    ref = make_engine("paged", park_max_s=0.0)
    try:
        p = ref.submit(prompt, sp).result(180)
    finally:
        ref.stop()
    assert r.tokens == p.tokens and r.text == p.text


def test_parked_slot_yields_under_pool_pressure():
    """Parked pages are speculative capacity: when the pool runs dry they
    are released (voluntarily, before any live slot is preempted) so new
    admissions never starve behind a parked conversation."""
    eng = make_engine("paged", kv_pages=18, max_slots=2)
    try:
        sp = SamplingParams(
            temperature=0.0, max_tokens=8, forced_prefix=tuple(TOK.encode(TWO_CALLS))
        )
        eng.submit("a" * 40, sp, on_tool_call=lambda i, tc: None, park=True).result(120)
        assert eng.stats()["parked_slots"] == 1
        # a fat unrelated burst needs the parked pages
        futs = [
            eng.submit(ch * 60, SamplingParams(temperature=0.0, max_tokens=24))
            for ch in "bc"
        ]
        for f in futs:
            f.result(120)
        st = eng.stats()
        assert st["parked_slots"] == 0
        assert st["tool_overlap"]["park_releases"] >= 1
    finally:
        eng.stop()


def test_force_preempt_lands_on_parked_slot_first():
    """faults: engine.force_preempt while a parked slot and a live slot
    coexist — the parked slot is the victim (voluntary release, no work
    lost), and the live generation completes un-preempted."""
    eng = make_engine("paged")
    try:
        sp = SamplingParams(
            temperature=0.0, max_tokens=8, forced_prefix=tuple(TOK.encode(TWO_CALLS))
        )
        eng.submit("conversation one " * 3, sp, park=True).result(120)
        assert eng.stats()["parked_slots"] == 1
        FAULTS.arm("engine.force_preempt", times=1)
        live = eng.submit(
            "unrelated work", SamplingParams(temperature=0.0, max_tokens=24)
        ).result(120)
        assert live.preempt_count == 0  # the parked slot absorbed the fault
        st = eng.stats()
        assert st["parked_slots"] == 0
        assert st["tool_overlap"]["park_releases"] == 1
        assert st["preemptions"] == 0  # a park release is not a preemption
    finally:
        eng.stop()


def test_unclaimed_park_expires():
    eng = make_engine("slot", park_max_s=0.3)
    try:
        sp = SamplingParams(
            temperature=0.0, max_tokens=6, forced_prefix=tuple(TOK.encode(TWO_CALLS))
        )
        eng.submit("final answer turn " * 3, sp, park=True).result(120)
        assert eng.stats()["parked_slots"] == 1
        deadline = time.monotonic() + 10
        while eng.stats()["parked_slots"] and time.monotonic() < deadline:
            time.sleep(0.05)
        st = eng.stats()
        assert st["parked_slots"] == 0
        assert st["tool_overlap"]["park_releases"] == 1
    finally:
        eng.stop()


def test_full_house_of_parked_slots_never_blocks_admission():
    """Every slot parked: a new, unrelated prompt must still admit (the
    LRU parked slot yields its slot index)."""
    eng = make_engine("slot", max_slots=2)
    try:
        sp = SamplingParams(
            temperature=0.0, max_tokens=4, forced_prefix=tuple(TOK.encode(TWO_CALLS))
        )
        eng.submit("conv A " * 4, sp, park=True).result(120)
        eng.submit("conv B " * 4, sp, park=True).result(120)
        assert eng.stats()["parked_slots"] == 2
        r = eng.submit(
            "conv C brand new", SamplingParams(temperature=0.0, max_tokens=4)
        ).result(120)
        assert r.finish_reason in ("stop", "length")
        st = eng.stats()
        assert st["tool_overlap"]["park_releases"] >= 1
    finally:
        eng.stop()


def test_early_events_survive_preempt_resume_without_replay():
    """A request preempted mid-decode and resumed must neither drop nor
    re-emit its early tool calls: the parser rides the request, and resume
    streams only fresh tokens."""
    eng = make_engine("paged", kv_pages=24, max_slots=2)
    try:
        events = []
        lock = threading.Lock()

        def on_tc(i, tc):
            with lock:
                events.append((i, tc.function.name))

        # both admit together (11 pages each of 23), then grow past the pool
        sp = SamplingParams(
            temperature=0.0, max_tokens=40,
            forced_prefix=tuple(TOK.encode(TWO_CALLS)),
        )
        with eng.hold_admission():
            futs = [
                eng.submit(ch * 16, sp, on_tool_call=on_tc) for ch in "ab"
            ]
        results = [f.result(180) for f in futs]
        assert sum(r.preempt_count for r in results) >= 1  # pressure did preempt
        with lock:
            # exactly one (0, t1) + one (1, t2) pair per request — no replay
            assert sorted(events) == [(0, "t1"), (0, "t1"), (1, "t2"), (1, "t2")]
    finally:
        eng.stop()
