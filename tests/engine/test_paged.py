"""Paged KV cache: reference ops, page allocator, and the Pallas kernel
(interpreter mode) against dense attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentcontrolplane_tpu.ops.attention import decode_attention
from agentcontrolplane_tpu.ops.paged import (
    PageAllocator,
    TRASH_PAGE,
    init_kv_pages,
    paged_decode_attention_reference,
    write_prompt_to_pages,
    write_token_to_pages,
)
from agentcontrolplane_tpu.ops.pallas.paged_attention import paged_decode_attention


def _setup(seed=0, S=3, H=4, Hkv=2, d=8, P=4, max_pages=6, num_pages=32):
    """Build a paged cache and an equivalent slot cache with random KV."""
    rng = np.random.default_rng(seed)
    seq_lens = np.asarray([9, 4, 17][:S], dtype=np.int32)
    q = jnp.asarray(rng.normal(size=(S, H, d)), dtype=jnp.float32)

    k_pages = jnp.zeros((num_pages, P, Hkv, d), dtype=jnp.float32)
    v_pages = jnp.zeros((num_pages, P, Hkv, d), dtype=jnp.float32)
    C = max_pages * P
    k_slot = np.zeros((S, C, Hkv, d), dtype=np.float32)
    v_slot = np.zeros((S, C, Hkv, d), dtype=np.float32)

    alloc = PageAllocator(num_pages)
    tables = np.full((S, max_pages), TRASH_PAGE, dtype=np.int32)
    for s in range(S):
        n = -(-int(seq_lens[s]) // P)
        pages = alloc.alloc(n)
        tables[s, :n] = pages
        kv = rng.normal(size=(2, int(seq_lens[s]), Hkv, d)).astype(np.float32)
        k_slot[s, : seq_lens[s]] = kv[0]
        v_slot[s, : seq_lens[s]] = kv[1]
        for j, page in enumerate(pages):
            lo, hi = j * P, min((j + 1) * P, int(seq_lens[s]))
            k_pages = k_pages.at[page, : hi - lo].set(kv[0][lo:hi])
            v_pages = v_pages.at[page, : hi - lo].set(kv[1][lo:hi])
    return q, k_pages, v_pages, jnp.asarray(tables), jnp.asarray(seq_lens), (
        jnp.asarray(k_slot), jnp.asarray(v_slot),
    )


def test_reference_paged_matches_slot_attention():
    q, k_pages, v_pages, tables, seq_lens, (k_slot, v_slot) = _setup()
    dense = decode_attention(q, k_slot, v_slot, seq_lens)
    paged = paged_decode_attention_reference(q, k_pages, v_pages, tables, seq_lens)
    np.testing.assert_allclose(np.asarray(paged), np.asarray(dense), rtol=1e-5, atol=1e-5)


def test_pallas_kernel_matches_reference_interpret():
    q, k_pages, v_pages, tables, seq_lens, _ = _setup()
    ref = paged_decode_attention_reference(q, k_pages, v_pages, tables, seq_lens)
    out = paged_decode_attention(q, k_pages, v_pages, tables, seq_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pallas_kernel_gqa_and_bigger_shapes():
    q, k_pages, v_pages, tables, seq_lens, _ = _setup(
        seed=1, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    ref = paged_decode_attention_reference(q, k_pages, v_pages, tables, seq_lens)
    out = paged_decode_attention(q, k_pages, v_pages, tables, seq_lens, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_write_token_and_prompt_roundtrip():
    P, Hkv, d = 4, 2, 8
    pages = init_kv_pages(1, 16, P, Hkv, d, jnp.float32)
    k_pages, v_pages = pages["k"][0], pages["v"][0]
    rng = np.random.default_rng(0)

    # prompt of 6 tokens -> pages [3, 5] (2 pages, second half-filled)
    prompt_k = jnp.asarray(rng.normal(size=(8, Hkv, d)), dtype=jnp.float32)
    prompt_v = jnp.asarray(rng.normal(size=(8, Hkv, d)), dtype=jnp.float32)
    page_ids = jnp.asarray([3, 5], dtype=jnp.int32)
    k_pages, v_pages = write_prompt_to_pages(k_pages, v_pages, page_ids, prompt_k, prompt_v)
    np.testing.assert_array_equal(np.asarray(k_pages[3]), np.asarray(prompt_k[:4]))
    np.testing.assert_array_equal(np.asarray(k_pages[5]), np.asarray(prompt_k[4:8]))

    # decode token at position 6 for slot with table [3,5] -> page 5 offset 2
    tables = jnp.asarray([[3, 5, 0]], dtype=jnp.int32)
    tok_k = jnp.asarray(rng.normal(size=(1, Hkv, d)), dtype=jnp.float32)
    tok_v = jnp.asarray(rng.normal(size=(1, Hkv, d)), dtype=jnp.float32)
    k_pages, v_pages = write_token_to_pages(
        k_pages, v_pages, tables, jnp.asarray([6]), jnp.asarray([True]), tok_k, tok_v
    )
    np.testing.assert_array_equal(np.asarray(k_pages[5, 2]), np.asarray(tok_k[0]))

    # inactive slot writes land in the trash page
    k_before = np.asarray(k_pages[5])
    k_pages, v_pages = write_token_to_pages(
        k_pages, v_pages, tables, jnp.asarray([7]), jnp.asarray([False]), tok_k, tok_v
    )
    np.testing.assert_array_equal(np.asarray(k_pages[5]), k_before)
    np.testing.assert_array_equal(np.asarray(k_pages[TRASH_PAGE, 3]), np.asarray(tok_k[0]))


def test_page_allocator():
    a = PageAllocator(8)
    assert a.free_count == 7  # page 0 reserved
    p1 = a.alloc(3)
    assert TRASH_PAGE not in p1
    a.free(p1)
    assert a.free_count == 7
    with pytest.raises(MemoryError):
        a.alloc(8)


def test_pallas_cache_plus_new_matches_reference_interpret():
    """The serving hot-path form (read-only pages + self term, merged from
    the kernel's unnormalized (acc, m, l)) == the exact XLA reference."""
    from agentcontrolplane_tpu.ops.paged import (
        paged_decode_attention_reference_cache_plus_new,
    )
    from agentcontrolplane_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_cache_plus_new,
    )

    for seed, kw in ((3, {}), (4, dict(S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16))):
        q, k_pages, v_pages, tables, seq_lens, _ = _setup(seed=seed, **kw)
        rng = np.random.default_rng(seed + 10)
        Hkv, d = k_pages.shape[2], k_pages.shape[3]
        S = q.shape[0]
        k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
        ref = paged_decode_attention_reference_cache_plus_new(
            q, k_pages, v_pages, tables, seq_lens, k_new, v_new
        )
        out = paged_decode_attention_cache_plus_new(
            q, k_pages, v_pages, tables, seq_lens, k_new, v_new, interpret=True
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_reference_cache_plus_new_equals_write_then_attend():
    """The self-term form must equal writing the token then attending —
    the two decode formulations are semantically identical."""
    from agentcontrolplane_tpu.ops.paged import (
        paged_decode_attention_reference_cache_plus_new,
    )

    q, k_pages, v_pages, tables, seq_lens, _ = _setup(seed=5)
    rng = np.random.default_rng(15)
    S, (Hkv, d) = q.shape[0], k_pages.shape[2:]
    k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    active = jnp.ones(S, dtype=bool)
    with_self = paged_decode_attention_reference_cache_plus_new(
        q, k_pages, v_pages, tables, seq_lens, k_new, v_new
    )
    kw, vw = write_token_to_pages(
        k_pages, v_pages, tables, seq_lens, active, k_new, v_new
    )
    written = paged_decode_attention_reference(q, kw, vw, tables, seq_lens + 1)
    np.testing.assert_allclose(
        np.asarray(with_self), np.asarray(written), rtol=1e-5, atol=1e-5
    )


def test_pallas_cache_plus_new_sharded_tp2_interpret():
    from agentcontrolplane_tpu.ops.paged import (
        paged_decode_attention_reference_cache_plus_new,
    )
    from agentcontrolplane_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_cache_plus_new_sharded,
    )
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    q, k_pages, v_pages, tables, seq_lens, _ = _setup(
        seed=6, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    rng = np.random.default_rng(16)
    S, (Hkv, d) = q.shape[0], k_pages.shape[2:]
    k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    ref = paged_decode_attention_reference_cache_plus_new(
        q, k_pages, v_pages, tables, seq_lens, k_new, v_new
    )
    out = paged_decode_attention_cache_plus_new_sharded(
        mesh, q, k_pages, v_pages, tables, seq_lens, k_new, v_new, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pallas_kernel_sharded_tp2_interpret():
    """shard_map wrapper over head-sharded pages (tp=2) == reference."""
    import jax

    from agentcontrolplane_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_sharded,
    )
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    q, k_pages, v_pages, tables, seq_lens, _ = _setup(
        seed=2, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    ref = paged_decode_attention_reference(q, k_pages, v_pages, tables, seq_lens)
    out = paged_decode_attention_sharded(
        mesh, q, k_pages, v_pages, tables, seq_lens, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_pallas_cache_plus_new_sp_sharded_interpret():
    """Context-parallel kernel wrapper (sp=4 x tp=2): each rank runs the
    kernel over its within-page slice and the unnormalized (acc, m, l)
    states merge across sp with pmax + psum — result == exact reference."""
    from agentcontrolplane_tpu.ops.paged import (
        paged_decode_attention_reference_cache_plus_new,
    )
    from agentcontrolplane_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_cache_plus_new_sharded,
    )
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    q, k_pages, v_pages, tables, seq_lens, _ = _setup(
        seed=9, S=3, H=8, Hkv=2, d=16, P=8, max_pages=4, num_pages=16
    )
    rng = np.random.default_rng(19)
    S, (Hkv, d) = q.shape[0], k_pages.shape[2:]
    k_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    v_new = jnp.asarray(rng.normal(size=(S, Hkv, d)), dtype=jnp.float32)
    ref = paged_decode_attention_reference_cache_plus_new(
        q, k_pages, v_pages, tables, seq_lens, k_new, v_new
    )
    for axes in ({"sp": 4, "tp": 2}, {"sp": 2, "tp": 1}):
        n = axes["sp"] * axes["tp"]
        mesh = make_mesh(axes, devices=jax.devices()[:n])
        out = paged_decode_attention_cache_plus_new_sharded(
            mesh, q, k_pages, v_pages, tables, seq_lens, k_new, v_new,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5,
            err_msg=str(axes),
        )
