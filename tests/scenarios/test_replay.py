"""Deterministic trace replay (scenarios/replay.py + library.py): the
byte-identity contract (same trace + same seed -> same tokens, per KV
layout, with speculation and chunked prefill on), scenario outcome shapes
(cancel churn, tool swarms, fault cocktails), and fleet replay with
stitched cross-replica phase attribution."""

from __future__ import annotations

import dataclasses

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.flight import attribute_phases
from agentcontrolplane_tpu.observability.trace_export import (
    export_fleet_trace,
    export_trace,
    stitched_fleet_timelines,
    validate_trace,
)
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.scenarios import (
    SCENARIOS,
    build,
    byte_identical,
    replay,
    synth_prompt,
)
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(
    PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2
)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def make_engine(kv_layout="paged", **kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout=kv_layout,
        page_size=8, **kw,
    )
    eng.start()
    return eng


def teardown(router, *engines):
    router.stop()
    for eng in engines:
        try:
            eng.stop()
        except Exception:
            pass


# -- pure: synthetic content + the library ---------------------------------


def test_synth_prompt_is_deterministic_and_persona_shared():
    a = synth_prompt(7, "abcd", 16, 40, 3)
    b = synth_prompt(7, "abcd", 16, 40, 3)
    assert a == b and len(a) == 40
    other_index = synth_prompt(7, "abcd", 16, 40, 4)
    assert other_index[:16] == a[:16]      # persona prefix shared
    assert other_index[16:] != a[16:]      # per-request body differs
    assert synth_prompt(8, "abcd", 16, 40, 3) != a   # seed is load-bearing
    # replay prompts must not accidentally open tool-call or tag syntax
    assert "{" not in a and "<" not in a


def test_every_library_scenario_emits_a_valid_trace():
    for name, gen in SCENARIOS.items():
        doc = gen()
        assert validate_trace(doc) == [], name
        assert doc["source"] == f"scenario:{name}"
        assert doc["requests"], name
        offsets = [r["offset_s"] for r in doc["requests"]]
        assert offsets == sorted(offsets), name


def test_cancel_churn_trace_carries_doom_and_throttle():
    doc = build("cancel_churn", n=6)
    cancels = [r for r in doc["requests"] if "cancel_after_s" in r]
    deadlines = [r for r in doc["requests"] if "deadline_s" in r]
    assert cancels and deadlines
    for r in cancels + deadlines:
        assert r["output_tokens"] > doc["requests"][0]["output_tokens"]
    assert any(f["site"] == "engine.slow_cycle" for f in doc["faults"])


# -- byte-identity: the replay determinism contract ------------------------


@pytest.mark.parametrize("kv_layout", ["slot", "paged"])
def test_live_trace_replays_byte_identical(kv_layout):
    """Acceptance: record a trace off live traffic, replay it (twice) at
    1x on the warmed engine — with speculation and chunked prefill on —
    and the two replays' greedy outputs are byte-identical per request."""
    eng = make_engine(kv_layout, spec_len=6, prefill_chunk=16)
    try:
        eng.prewarm(constrained=True)
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        live = [
            "persona alpha shares this long prefix // req one",
            "persona alpha shares this long prefix // req two",
            "persona beta is its own prompt shape",
        ]
        for f in [eng.submit(p, sp) for p in live]:
            f.result(timeout=120)
        trace = export_trace(eng.flight)
        assert validate_trace(trace) == []
        # >= because prewarm's warmup bursts go through submit() and are
        # recorded too — they replay like any other traffic
        assert len(trace["requests"]) >= 3
        a = replay(trace, eng, speed=1.0, seed=5, record_metrics=False)
        b = replay(trace, eng, speed=1.0, seed=5, record_metrics=False)
        assert a.count("completed") == len(trace["requests"])
        assert byte_identical(a, b)
        # a different seed is a different workload (same shape)
        c = replay(trace, eng, speed=1.0, seed=6, record_metrics=False)
        assert not byte_identical(a, c)
    finally:
        eng.stop()


# -- scenario outcome shapes ----------------------------------------------


def test_cancel_churn_replay_exercises_cleanup_paths():
    """On a cold engine the first prefill compiles while the rest queue:
    cancel timers land on queued/running requests and tight deadlines
    expire in the admission queue — and none of it surfaces as an error."""
    eng = make_engine()  # no prewarm, deliberately cold
    try:
        trace = build(
            "cancel_churn", n=8, prompt_tokens=16, output_tokens=4,
            doomed_output_tokens=40, slow_cycles=80,
        )
        report = replay(trace, eng, scenario="cancel_churn")
        doc = report.slo_doc()
        assert doc["errors"] == 0
        assert doc["cancelled"] >= 1
        assert doc["expired"] >= 1
        total = (
            doc["completed"] + doc["cancelled"] + doc["expired"]
            + doc["shed"] + doc["errors"]
        )
        assert total == doc["requests"] == 8
    finally:
        eng.stop()


def test_tool_swarm_replay_fires_tool_callbacks():
    eng = make_engine()
    try:
        eng.prewarm(constrained=True)
        trace = build(
            "tool_swarm", n=3, tools_per_request=1, prompt_tokens=16,
            output_tokens=8, slow_tools=2, tool_delay_s=0.01,
        )
        report = replay(trace, eng, scenario="tool_swarm")
        doc = report.slo_doc()
        assert doc["completed"] == 3
        assert doc["tool_calls"] == 3  # one forced envelope per request
    finally:
        eng.stop()


def test_fault_cocktail_replay_arms_the_switchboard():
    eng = make_engine()
    try:
        eng.prewarm(constrained=True)
        trace = build(
            "fault_cocktail", n=6, prompt_tokens=16, output_tokens=4,
            preempts=1, queue_fulls=1,
        )
        report = replay(trace, eng, scenario="fault_cocktail")
        doc = report.slo_doc()
        assert doc["shed"] == 1       # engine.queue_full surfaced as a shed
        assert doc["errors"] == 0
        assert doc["completed"] + doc["shed"] == 6
    finally:
        eng.stop()


def test_scenario_metrics_are_emitted():
    from agentcontrolplane_tpu.observability.metrics import REGISTRY

    eng = make_engine()
    try:
        eng.prewarm(constrained=True)
        trace = build("persona_storm", n=4, prompt_tokens=24,
                      prefix_tokens=16, output_tokens=4)
        replay(trace, eng, scenario="persona_storm")
        text = REGISTRY.render()
        assert 'acp_scenario_requests_total{outcome="completed",scenario="persona_storm"}' in text or \
               'acp_scenario_requests_total{scenario="persona_storm",outcome="completed"}' in text
        assert "acp_scenario_ttft_seconds" in text
        assert "acp_scenario_decode_stall_seconds" in text
    finally:
        eng.stop()


# -- fleet replay + stitched phase attribution -----------------------------


def test_fleet_replay_stitched_phases_sum_once():
    """Replay against a disaggregated pool, then stitch each request's
    router + prefill + decode legs: attributed phases must sum to the
    caller-visible end-to-end once — the per-leg naive sum double-counts
    queue_wait (each replica re-queues the request), the stitched
    timeline must not."""
    router = FleetRouter(store=Store(), handoff_min_tokens=8,
                         heartbeat_interval=60.0)
    prefill = make_engine()
    decode = make_engine()
    router.add_replica("pf", prefill, role="prefill")
    router.add_replica("dc", decode, role="decode")
    try:
        trace = build("persona_storm", n=6, prompt_tokens=24,
                      prefix_tokens=16, output_tokens=4)
        report = replay(trace, router, scenario="persona_storm")
        assert report.count("completed") == 6
        stitched, missing = stitched_fleet_timelines(router)
        assert stitched and missing == 0
        checked = 0
        for rid, events in stitched.items():
            kinds = [e["kind"] for e in events]
            if "handoff_submit" not in kinds:
                continue  # degraded to a local prefill — nothing to stitch
            durations, spans = attribute_phases(events)
            submit_t = next(e["t"] for e in events if e["kind"] == "submit")
            end_t = max(e["t"] for e in events)
            e2e = end_t - submit_t
            phase_sum = sum(durations.values())
            assert phase_sum == pytest.approx(e2e, rel=0.05, abs=0.005), rid
            # the stitched view keeps exactly one admission edge
            assert kinds.count("admit") == 1
            checked += 1
        assert checked >= 1
        fleet_doc = export_fleet_trace(router)
        assert validate_trace(fleet_doc) == []
        assert len(fleet_doc["requests"]) == 6
    finally:
        teardown(router, prefill, decode)


# -- compressed-time replays (slow tier) -----------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("speed", [10.0, 100.0])
def test_replay_speed_compression_stays_deterministic(speed):
    eng = make_engine(spec_len=6, prefill_chunk=16)
    try:
        eng.prewarm(constrained=True)
        trace = build("persona_storm", n=8, prompt_tokens=24,
                      prefix_tokens=16, output_tokens=6)
        a = replay(trace, eng, speed=speed, seed=3, record_metrics=False)
        b = replay(trace, eng, speed=speed, seed=3, record_metrics=False)
        assert a.count("completed") == 8
        assert byte_identical(a, b)
    finally:
        eng.stop()


@pytest.mark.slow
def test_replay_100x_compresses_wall_clock():
    eng = make_engine()
    try:
        eng.prewarm(constrained=True)
        trace = build("long_tail", n=8, long_tokens=40, interval_s=0.5)
        fast = replay(trace, eng, speed=100.0, record_metrics=False)
        assert fast.count("completed") == 8
        # a 3.5s arrival span compressed 100x: the run is dominated by
        # decode, not by sleeping out the schedule
        assert fast.wall_s < 2.0
    finally:
        eng.stop()
