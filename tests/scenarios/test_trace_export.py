"""Trace export (observability/trace_export.py): anonymization, the
stitched cross-replica attribution contract (queue_wait counted once — the
PR's pinned bugfix), and the no-silent-truncation guarantees around the
flight recorder's bounded windows."""

from __future__ import annotations

import dataclasses

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.observability.flight import (
    FlightRecorder,
    attribute_phases,
)
from agentcontrolplane_tpu.observability.trace_export import (
    TRACE_VERSION,
    export_trace,
    stitch_timelines,
    validate_trace,
)
from agentcontrolplane_tpu.parallel.mesh import make_mesh

TOK = ByteTokenizer()
CFG = dataclasses.replace(
    PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2
)


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout="paged",
        page_size=8, **kw,
    )
    eng.start()
    return eng


# -- stitching: the queue_wait double-count bugfix (pure, no engine) -------


def _ev(seq, t, kind, **detail):
    out = {"seq": seq, "t": t, "kind": kind}
    if detail:
        out["detail"] = detail
    return out


def _disagg_legs():
    """A synthetic disaggregated request: router timeline + a prefill
    probe leg + the decode leg, each with its own submit/admit lifecycle
    (exactly what two independent recorders capture today)."""
    origin = [
        _ev(1, 10.0, "submit", prompt_tokens=40, key="p"),
        _ev(2, 10.001, "handoff_start", prefill="pf", engine_rid="e1"),
        _ev(3, 10.9, "finish", reason="stop", tokens=8),
    ]
    prefill = [
        _ev(1, 10.002, "submit", prompt_tokens=40),
        _ev(2, 10.102, "admit"),        # queue_wait leg 1: 100ms
        _ev(3, 10.302, "prefill_done"),  # the 1-token probe
        _ev(4, 10.303, "finish", reason="length", tokens=1),
    ]
    decode = [
        _ev(1, 10.35, "submit", prompt_tokens=40),
        _ev(2, 10.55, "admit"),         # queue_wait leg 2: 200ms
        _ev(3, 10.65, "prefill_done"),  # caller-visible first token
        _ev(4, 10.9, "finish", reason="stop", tokens=8),
    ]
    return [("origin", origin), ("prefill", prefill), ("attempt", decode)]


def test_naive_per_leg_sum_double_counts_queue_wait():
    """The bug being fixed, pinned: attributing each replica's leg
    independently and summing counts queue_wait twice (once per leg)."""
    legs = _disagg_legs()
    total_queue = sum(
        attribute_phases(events)[0].get("queue_wait", 0.0)
        for _, events in legs
    )
    assert total_queue == pytest.approx(0.3, abs=1e-6)  # 0.1 + 0.2 — wrong


def test_stitched_timeline_counts_queue_wait_once_and_sums_to_e2e():
    """Stitched: queue_wait = arrival -> FIRST admission anywhere in the
    pool (the prefill replica's, here); the decode replica's own wait is
    transfer latency inside prefill; phases sum to ~end-to-end."""
    stitched = stitch_timelines(_disagg_legs())
    durations, _ = attribute_phases(stitched)
    # arrival 10.0 (router submit) -> prefill admit 10.102
    assert durations["queue_wait"] == pytest.approx(0.102, abs=1e-6)
    # first admission -> caller-visible first token (decode leg's)
    assert durations["prefill"] == pytest.approx(10.65 - 10.102, abs=1e-6)
    assert durations["decode"] == pytest.approx(10.9 - 10.65, abs=1e-6)
    total = (
        durations["queue_wait"] + durations["prefill"] + durations["decode"]
    )
    assert total == pytest.approx(0.9, abs=1e-6)  # submit 10.0 -> finish 10.9


def test_stitch_rewrites_non_final_edges():
    stitched = stitch_timelines(_disagg_legs())
    kinds = [e["kind"] for e in stitched]
    assert kinds.count("submit") == 1
    assert kinds.count("admit") == 1
    assert kinds.count("prefill_done") == 1  # the decode leg's
    assert kinds.count("finish") == 1        # the globally last terminal
    assert "handoff_submit" in kinds and "handoff_admit" in kinds
    assert "handoff_prefill_done" in kinds and "handoff_finish" in kinds
    # seq renumbered monotonically over the merged order
    assert [e["seq"] for e in stitched] == list(range(1, len(stitched) + 1))


def test_stitch_failover_keeps_crashed_attempts_first_token():
    """A failover retry: the crashed attempt streamed caller-visible
    tokens, so ITS prefill_done is the request's first token — attempt
    legs keep prefill_done, only the prefill role loses it."""
    origin = [
        _ev(1, 5.0, "submit", prompt_tokens=10, key="p"),
        _ev(2, 6.0, "finish", reason="stop", tokens=6),
    ]
    crashed = [
        _ev(1, 5.001, "submit"), _ev(2, 5.1, "admit"),
        _ev(3, 5.2, "prefill_done"),
    ]
    retry = [
        _ev(1, 5.4, "submit"), _ev(2, 5.5, "admit"),
        _ev(3, 5.6, "prefill_done"),
        _ev(4, 5.99, "finish", reason="stop", tokens=6),
    ]
    stitched = stitch_timelines(
        [("origin", origin), ("attempt", crashed), ("attempt", retry)]
    )
    durations, _ = attribute_phases(stitched)
    assert durations["queue_wait"] == pytest.approx(0.1, abs=1e-6)
    # first token stays the crashed attempt's (5.2), decode runs to the
    # router finish (6.0)
    assert durations["prefill"] == pytest.approx(0.1, abs=1e-6)
    assert durations["decode"] == pytest.approx(0.8, abs=1e-6)


# -- single-engine export --------------------------------------------------


def test_export_is_anonymized_and_replayable():
    eng = make_engine()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        prompts = ["shared persona prefix A!", "shared persona prefix A!",
                   "a different one entirely"]
        for f in [eng.submit(p, sp) for p in prompts]:
            f.result(timeout=120)
        doc = export_trace(eng.flight)
        assert doc["version"] == TRACE_VERSION
        assert doc["anonymized"] is True and doc["complete"] is True
        assert validate_trace(doc) == []
        rows = doc["requests"]
        assert len(rows) == 3
        # no content anywhere: only lengths, offsets, hashes
        for row in rows:
            assert set(row) <= {
                "i", "offset_s", "prompt_tokens", "output_tokens", "persona",
                "finish", "deadline_s", "cancel_after_s", "tool_calls",
            }
            assert row["prompt_tokens"] == len(prompts[0])  # ASCII 1:1
            assert 1 <= row["output_tokens"] <= 4  # actual, EOS may cut in
            assert len(row["persona"]) == 16
        # the two same-prefix prompts share a persona fingerprint
        personas = [r["persona"] for r in rows]
        assert len(set(personas)) == 2
        shared = [k for k, v in doc["personas"].items() if v["requests"] == 2]
        assert len(shared) == 1
        assert doc["personas"][shared[0]]["prefix_tokens"] > 0
    finally:
        eng.stop()


def test_validate_trace_rejects_malformed_docs():
    assert validate_trace([]) == ["trace is not a JSON object"]
    assert any("version" in p for p in validate_trace({"version": 99}))
    bad = {
        "version": TRACE_VERSION,
        "requests": [
            {"offset_s": 1.0, "prompt_tokens": 4, "output_tokens": 1},
            {"offset_s": 0.5, "prompt_tokens": -1, "output_tokens": 1},
        ],
    }
    probs = validate_trace(bad)
    assert any("decreases" in p for p in probs)
    assert any("prompt_tokens" in p for p in probs)


# -- no-silent-truncation: window roll + finished-LRU eviction -------------


def test_timelines_survive_global_window_roll():
    """The global deque rolling must not cost per-request replayability:
    a recorder whose window holds 16 events still renders every event of
    every request (the _by_rid index is independent of the deque)."""
    rec = FlightRecorder(capacity=16, enabled=True, finished_timelines=64)
    rids = [f"r{i}" for i in range(8)]
    for i, rid in enumerate(rids):
        rec.record("submit", rid=rid, prompt_tokens=4)
        rec.record("admit", rid=rid)
        rec.record("prefill_done", rid=rid)
        rec.finish(rid, "stop", tokens=2)
    stats = rec.stats()
    assert stats["window_events"] == 16          # the window DID roll
    assert stats["recorded_total"] == 32
    assert stats["evicted_timelines"] == 0
    doc = export_trace(rec)
    assert doc["complete"] is True
    assert len(doc["requests"]) == 8             # nothing truncated
    for rid in rids:
        assert [e["kind"] for e in rec.timeline(rid)] == [
            "submit", "admit", "prefill_done", "finish",
        ]


def test_finished_lru_eviction_is_counted_not_silent():
    """What CAN truncate an export is the finished-timeline LRU; the
    recorder counts evictions and the trace doc drops its ``complete``
    verdict instead of quietly shipping a short request list."""
    rec = FlightRecorder(capacity=256, enabled=True, finished_timelines=2)
    for i in range(5):
        rid = f"r{i}"
        rec.record("submit", rid=rid, prompt_tokens=4)
        rec.finish(rid, "stop", tokens=1)
    stats = rec.stats()
    assert stats["finished_timelines"] == 2
    assert stats["finished_timeline_cap"] == 2
    assert stats["evicted_timelines"] == 3
    doc = export_trace(rec)
    assert doc["complete"] is False
    assert doc["flight"]["evicted_timelines"] == 3
    assert len(doc["requests"]) == 2


def test_flight_timelines_env_knob(monkeypatch):
    monkeypatch.setenv("ACP_FLIGHT_TIMELINES", "7")
    rec = FlightRecorder(enabled=True)
    assert rec.stats()["finished_timeline_cap"] == 7
