"""scenarios/chaos.py: the seeded chaos conductor — schedule purity
(same seed => same cocktail), ledger reproducibility, a live fleet
surviving a full chaos run with the invariants armed, and the
gray-failure acceptance test (persona storm over a fleet with one
throttled replica, byte-identical to a clean single engine)."""

from __future__ import annotations

import dataclasses
import time

import jax
import pytest

from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.fleet.health import HealthPolicy
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.models.llama import PRESETS
from agentcontrolplane_tpu.parallel.mesh import make_mesh
from agentcontrolplane_tpu.scenarios import build, byte_identical, replay
from agentcontrolplane_tpu.scenarios.chaos import (
    ChaosConductor,
    chaos_schedule,
    run_chaos,
)
from agentcontrolplane_tpu.testing import FAULTS

TOK = ByteTokenizer()
CFG = dataclasses.replace(
    PRESETS["tiny"], vocab_size=512, max_seq_len=256, n_kv_heads=2
)

STORM_KW = dict(n=6, personas=2, prompt_tokens=24, prefix_tokens=16,
                output_tokens=8)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def make_engine(**kw):
    mesh = make_mesh({"tp": 2}, devices=jax.devices()[:2])
    kw.setdefault("check_invariants", True)
    eng = Engine(
        config=CFG, tokenizer=TOK, mesh=mesh, max_slots=4, max_ctx=64,
        prefill_buckets=(32, 64), decode_block_size=4, kv_layout="paged",
        page_size=8, **kw,
    )
    eng.start()
    return eng


def make_fleet(n=3, **router_kw):
    router = FleetRouter(store=Store(), heartbeat_interval=60.0, **router_kw)
    engines = [make_engine() for _ in range(n)]
    for i, eng in enumerate(engines):
        router.add_replica(f"r{i}", eng)
    return router, engines


def teardown(router, *engines):
    router.stop()
    for eng in engines:
        try:
            eng.stop()
        except Exception:
            pass


# -- pure: the schedule -------------------------------------------------------


def test_schedule_is_a_pure_function_of_the_seed():
    ids = ("r0", "r1", "r2")
    a = chaos_schedule(7, replica_ids=ids, span_s=2.0, tools=True)
    b = chaos_schedule(7, replica_ids=ids, span_s=2.0, tools=True)
    assert a == b
    assert a != chaos_schedule(8, replica_ids=ids, span_s=2.0, tools=True)
    # sorted by virtual offset; every event inside the span
    offsets = [e["offset_s"] for e in a]
    assert offsets == sorted(offsets)
    assert all(0.0 <= o <= 2.0 for o in offsets)


def test_schedule_keeps_a_healthy_majority():
    """The crash victim is never the throttled replica, and a schedule
    with fewer than two replicas never crashes anyone."""
    for seed in range(20):
        sched = chaos_schedule(seed, replica_ids=("r0", "r1", "r2"))
        by_site = {e["site"]: e for e in sched}
        slow_victim = by_site["engine.slow_cycle"]["spec"]["replica"]
        crash_victim = by_site["fleet.replica_crash"]["spec"]["replica"]
        assert crash_victim != slow_victim
    solo = chaos_schedule(3)  # single engine: no ids
    sites = [e["site"] for e in solo]
    assert "fleet.replica_crash" not in sites
    assert "fleet.handoff_error" not in sites
    assert "replica" not in next(
        e for e in solo if e["site"] == "engine.slow_cycle"
    )["spec"]


def test_conductor_ledger_matches_schedule_in_order():
    """The ledger is the reproducibility surface: every scheduled arm
    lands, in offset order, with the spec recorded verbatim."""
    sched = chaos_schedule(11, replica_ids=("r0", "r1"), span_s=0.2)
    conductor = ChaosConductor(sched, speed=10.0)
    conductor.start()
    deadline = time.monotonic() + 10.0
    while len(conductor.ledger) < len(sched) and time.monotonic() < deadline:
        time.sleep(0.01)
    conductor.stop()
    FAULTS.reset()  # the arms above enabled the switchboard
    assert conductor.ledger == [
        (e["offset_s"], e["site"], e["spec"]) for e in sched
    ]


# -- live: one seeded run + the acceptance test -------------------------------


def test_run_chaos_fleet_survives_and_ledger_reproduces():
    """One seed poured over a 3-replica fleet twice: both runs hold
    every invariant (conservation, exactly-once, zero errors) and arm
    the identical ledger — the CLI smoke tier runs exactly this."""
    reports = []
    for _ in range(2):
        router, engines = make_fleet(3)
        try:
            reports.append(
                run_chaos(router, seed=3, speed=20.0,
                          scenario_kwargs=dict(STORM_KW))
            )
        finally:
            teardown(router, *engines)
    for rep in reports:
        assert rep.ok(), rep.violations
        assert rep.seed == 3 and rep.scenario == "persona_storm"
        assert len(rep.ledger) == len(rep.schedule)
        assert rep.replay.count("completed") == STORM_KW["n"]
        doc = rep.doc()
        assert doc["ok"] and doc["armed"] and doc["slo"]["requests"] == 6
    assert reports[0].schedule == reports[1].schedule
    assert reports[0].ledger == reports[1].ledger
    # chaos must not leak arms into the caller's next run
    assert not FAULTS.enabled
    assert not any(FAULTS.armed(e["site"]) for e in reports[1].schedule)


@pytest.mark.slow
def test_chaos_soak_multiple_seeds():
    """Slow tier: several seeds, several cocktails — every one must hold
    the conservation invariants (latency envelopes deliberately not
    judged; chaos exists to stretch them)."""
    for seed in (0, 1, 2, 7):
        router, engines = make_fleet(3)
        try:
            rep = run_chaos(router, seed=seed, speed=20.0,
                            scenario_kwargs=dict(STORM_KW))
        finally:
            teardown(router, *engines)
        assert rep.ok(), (seed, rep.violations)


def test_gray_failure_acceptance_byte_identical_to_clean_engine():
    """THE acceptance test: a persona storm over a 3-replica fleet with
    one replica throttled gray (hedging on) completes every request
    exactly-once, byte-identical to the same trace on an unfaulted
    single engine."""
    trace = build("persona_storm", seed=5, **STORM_KW)
    baseline = make_engine()
    try:
        clean = replay(trace, baseline, speed=20.0, scenario="persona_storm")
    finally:
        baseline.stop()
    assert clean.count("completed") == STORM_KW["n"]

    router, engines = make_fleet(
        3, hedge_after_s=0.3, watchdog_interval_s=0.1,
        health_policy=HealthPolicy(degrade_after=1),
    )
    try:
        # honest post-compile cycles seed each replica's cadence floor
        sp = SamplingParams(temperature=0.0, max_tokens=16)
        for r in router.pool.replicas():
            r.engine.submit("warm the cadence floor", sp).result(timeout=120)
        FAULTS.arm("engine.slow_cycle", times=40, delay_s=0.1, replica="r0")
        gray = replay(trace, router, speed=20.0, scenario="persona_storm")
    finally:
        teardown(router, *engines)
    assert gray.count("completed") == STORM_KW["n"]
    assert gray.stream_violations() == []   # exactly-once, every request
    assert byte_identical(clean, gray)
