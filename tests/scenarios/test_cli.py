"""``acp-tpu trace export`` / ``acp-tpu replay`` CLI: exit codes, the
validate-before-write contract, scenario building with ``--set``
overrides, and the ``--gate`` envelope judgement — engine construction is
stubbed so these stay in the fast tier."""

from __future__ import annotations

import json
from concurrent.futures import Future
from types import SimpleNamespace

import pytest

from agentcontrolplane_tpu import cli
from agentcontrolplane_tpu.cli import main as cli_main
from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
from agentcontrolplane_tpu.observability.flight import FlightRecorder
from agentcontrolplane_tpu.observability.trace_export import validate_trace
from agentcontrolplane_tpu.scenarios import build
from agentcontrolplane_tpu.testing import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- replay: validation paths (no engine involved) --------------------------


def test_replay_check_validates_a_trace_file(tmp_path, capsys):
    path = tmp_path / "trace.json"
    path.write_text(json.dumps(build("persona_storm", n=5)))
    assert cli_main(["replay", str(path), "--check"]) == 0
    out = capsys.readouterr().out
    assert "5 request(s)" in out and "scenario:persona_storm" in out


def test_replay_rejects_bad_inputs(tmp_path, capsys):
    # no trace and no scenario
    assert cli_main(["replay"]) == 1
    # both at once
    assert cli_main(["replay", "x.json", "--scenario", "long_tail"]) == 1
    # missing file
    assert cli_main(["replay", str(tmp_path / "ghost.json"), "--check"]) == 1
    # malformed JSON
    bad = tmp_path / "bad.json"
    bad.write_text("{nope")
    assert cli_main(["replay", str(bad), "--check"]) == 1
    # valid JSON, unreplayable doc
    bad.write_text(json.dumps({"version": 999, "requests": []}))
    assert cli_main(["replay", str(bad), "--check"]) == 1
    # unknown scenario and malformed --set
    assert cli_main(["replay", "--scenario", "nope", "--check"]) == 1
    assert cli_main(
        ["replay", "--scenario", "long_tail", "--set", "garbage", "--check"]
    ) == 1
    err = capsys.readouterr().err
    assert "unreplayable" in err and "unknown scenario" in err


def test_replay_scenario_set_overrides(capsys):
    assert cli_main(
        ["replay", "--scenario", "cancel_churn", "--set", "n=6", "--check"]
    ) == 0
    assert "6 request(s)" in capsys.readouterr().out


# -- replay: the run + --gate exit codes (stubbed engine) -------------------


class _InstantEngine:
    """Duck-typed replay target: every request completes immediately with
    the same tokens — deterministic, fast, and envelope-friendly for
    persona_storm but (by construction) churn-free."""

    tokenizer = ByteTokenizer()

    def start(self):
        pass

    def stop(self):
        pass

    def submit(self, prompt, sampling=None, on_tokens=None, **kw):
        toks = [1, 2, 3]
        if on_tokens is not None:
            on_tokens(toks[:2])
            on_tokens(toks[2:])
        fut = Future()
        fut.set_result(SimpleNamespace(
            text="abc", tokens=toks, finish_reason="stop", preempt_count=0,
        ))
        return fut

    def cancel(self, fut):
        fut.cancel()

    def stats(self):
        return {"perf": {"goodput": {"ratio": 0.9}}}


@pytest.fixture
def instant_engine(monkeypatch):
    monkeypatch.setattr(cli, "_build_engine", lambda args: _InstantEngine())


def test_replay_run_prints_slo_json_and_passes_gate(instant_engine, capsys):
    rc = cli_main([
        "replay", "--scenario", "persona_storm", "--set", "n=4",
        "--no-prewarm", "--json", "--gate",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    slo = json.loads(out[:out.rindex("}") + 1])
    assert slo["scenario"] == "persona_storm"
    assert slo["completed"] == 4 and slo["errors"] == 0
    assert slo["goodput_ratio"] == 0.9
    assert "inside its envelope" in out


def test_replay_gate_violation_exits_2(instant_engine, capsys):
    # an instant engine never cancels or expires anything, so cancel_churn's
    # envelope (min_cancelled/min_expired floors) must trip
    rc = cli_main([
        "replay", "--scenario", "cancel_churn", "--set", "n=6",
        "--no-prewarm", "--gate",
    ])
    assert rc == 2
    out = capsys.readouterr().out
    assert "envelope violation" in out


# -- trace export against a live REST server --------------------------------


def _recorded_engine():
    rec = FlightRecorder(enabled=True)
    for i, rid in enumerate(("ra", "rb")):
        rec.record("submit", rid=rid, prompt_tokens=12 + i, key=f"k{i}")
        rec.record("admit", rid=rid)
        rec.record("prefill_done", rid=rid)
        rec.finish(rid, "stop", tokens=3)
    return SimpleNamespace(flight=rec)


def test_cli_trace_export_roundtrips_through_replay_check(tmp_path):
    """Export off a live server, then feed the written file straight back
    through ``replay --check``: an exit-0 export is a replayable trace."""
    import asyncio
    import threading

    from agentcontrolplane_tpu.operator import Operator, OperatorOptions

    started = threading.Event()
    port = {}
    box = {}

    def server_thread():
        async def run():
            op = Operator(options=OperatorOptions(
                enable_rest=True, api_port=0, llm_probe=False,
                verify_channel_credentials=False,
            ))
            op.engine = _recorded_engine()
            await op.start()
            while not op.rest_server.bound_port:
                await asyncio.sleep(0.01)
            port["p"] = op.rest_server.bound_port
            box["stop"] = asyncio.Event()
            started.set()
            await box["stop"].wait()
            await op.stop()

        loop = asyncio.new_event_loop()
        box["loop"] = loop
        loop.run_until_complete(run())

    t = threading.Thread(target=server_thread, daemon=True)
    t.start()
    assert started.wait(10)
    server = f"http://127.0.0.1:{port['p']}"
    try:
        out = tmp_path / "trace.json"
        assert cli_main(["--server", server, "trace", "export", "-o", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert validate_trace(doc) == []
        assert len(doc["requests"]) == 2
        # no fleet configured: the fleet arm fails loudly, not emptily
        assert cli_main(["--server", server, "trace", "export", "--fleet"]) == 1
        # the round trip: exported file -> replayer validation
        assert cli_main(["replay", str(out), "--check"]) == 0
    finally:
        box["loop"].call_soon_threadsafe(box["stop"].set)
        t.join(timeout=10)
