"""Manifest loading, /v1/apply + generic resource endpoints, CLI commands."""

import pytest

from agentcontrolplane_tpu.api.manifests import (
    apply_resources,
    dump_manifests,
    load_manifests,
    resource_from_manifest,
)
from agentcontrolplane_tpu.kernel.errors import Invalid

from .test_rest import RestHarness

GETTING_STARTED = open("examples/getting-started.yaml").read()


def test_load_manifests_camel_case(store):
    resources = load_manifests(GETTING_STARTED)
    kinds = [r.kind for r in resources]
    assert kinds == ["Secret", "LLM", "Agent", "Task"]
    llm = resources[1]
    assert llm.spec.api_key_from.name == "openai-key"
    task = resources[3]
    assert task.spec.agent_ref.name == "my-assistant"


def test_apply_create_then_configure(store):
    resources = load_manifests(GETTING_STARTED)
    results = apply_resources(store, resources)
    assert [a for a, _ in results] == ["created"] * 4

    # mutate a spec and set some status to prove status survives re-apply
    llm = store.get("LLM", "gpt-4o")
    llm.status.ready = True
    llm.status.status = "Ready"
    store.update_status(llm)

    text = GETTING_STARTED.replace("model: gpt-4o", "model: gpt-4o-mini")
    results = apply_resources(store, load_manifests(text))
    assert [a for a, _ in results] == ["configured"] * 4
    llm = store.get("LLM", "gpt-4o")
    assert llm.spec.parameters.model == "gpt-4o-mini"
    assert llm.status.ready  # status preserved by apply


def test_every_example_manifest_applies(store):
    """The examples/ gallery is a user-facing API surface: every file must
    load and apply cleanly against the current schema."""
    import glob

    paths = sorted(glob.glob("examples/*.yaml"))
    assert len(paths) >= 5
    for path in paths:
        resources = load_manifests(open(path).read())
        assert resources, f"{path} contains no resources"
        results = apply_resources(store, resources)
        assert all(action in ("created", "configured") for action, _ in results), (
            path, results,
        )


def test_run_refuses_tokenless_nonloopback_serve_store(monkeypatch):
    """Security gate the release bundles rely on: serving the store
    (Secrets + Leases read/write) on a non-loopback interface without a
    token must refuse at startup, loudly."""
    import agentcontrolplane_tpu.operator as operator_mod
    from agentcontrolplane_tpu.cli import main as cli_main

    # sentinel PAST the guard: cmd_run constructs OperatorOptions right
    # after the token check, so reaching it proves the guard admitted the
    # invocation — without starting a real operator. (An argparse error on
    # a bogus flag would exit before the guard even runs and prove
    # nothing.)
    class _GuardPassed(Exception):
        pass

    def _sentinel(**kwargs):
        raise _GuardPassed

    monkeypatch.setattr(operator_mod, "OperatorOptions", _sentinel)

    monkeypatch.delenv("ACP_STORE_TOKEN", raising=False)
    with pytest.raises(SystemExit, match="store-token"):
        cli_main(["run", "--serve-store", "tcp://0.0.0.0:8090"])
    # with a token the guard passes and cmd_run reaches the sentinel
    monkeypatch.setenv("ACP_STORE_TOKEN", "s3cret")
    with pytest.raises(_GuardPassed):
        cli_main(["run", "--serve-store", "tcp://0.0.0.0:8090"])
    # loopback and unix stay token-optional: the guard admits them with
    # NO token configured (the sentinel fires, not the SystemExit)
    monkeypatch.delenv("ACP_STORE_TOKEN", raising=False)
    for addr in ("tcp://127.0.0.1:8090", "unix:///tmp/acp-test-store.sock"):
        with pytest.raises(_GuardPassed):
            cli_main(["run", "--serve-store", addr])


def test_manifest_validation_errors(store):
    with pytest.raises(Invalid, match="unknown kind"):
        resource_from_manifest({"kind": "Nope", "metadata": {"name": "x"}})
    with pytest.raises(Invalid, match="metadata.name"):
        resource_from_manifest({"kind": "Task", "metadata": {}})
    with pytest.raises(Invalid, match="invalid Task"):
        resource_from_manifest({"kind": "Task", "metadata": {"name": "t"}, "spec": {}})


def test_dump_roundtrip(store):
    resources = load_manifests(GETTING_STARTED)
    text = dump_manifests(resources)
    again = load_manifests(text)
    assert [r.metadata.name for r in again] == [r.metadata.name for r in resources]


async def test_apply_endpoint_and_generic_resources():
    async with RestHarness() as h:
        resp = await h.http.post(f"{h.base}/v1/apply", data=GETTING_STARTED)
        assert resp.status == 200
        actions = await resp.json()
        assert {(a["kind"], a["action"]) for a in actions} == {
            ("Secret", "created"), ("LLM", "created"),
            ("Agent", "created"), ("Task", "created"),
        }
        resp = await h.http.get(f"{h.base}/v1/resources/Agent/my-assistant")
        body = await resp.json()
        assert body["spec"]["llm_ref"]["name"] == "gpt-4o"

        resp = await h.http.get(f"{h.base}/v1/resources/Task?labelSelector=acp.tpu/agent=x")
        assert await resp.json() == []  # selector filters

        resp = await h.http.delete(f"{h.base}/v1/resources/Task/hello-world-1")
        assert resp.status == 200
        resp = await h.http.get(f"{h.base}/v1/resources/Task/hello-world-1")
        assert resp.status == 404

        resp = await h.http.post(f"{h.base}/v1/apply", data="kind: Nope\nmetadata: {name: x}")
        assert resp.status == 400


def test_cli_get_apply_against_live_server(tmp_path):
    """Drive the CLI main() against a live operator REST server."""
    import asyncio
    import threading

    from agentcontrolplane_tpu.cli import main as cli_main
    from agentcontrolplane_tpu.llmclient import MockLLMClient, MockLLMClientFactory, assistant
    from agentcontrolplane_tpu.operator import Operator, OperatorOptions

    started = threading.Event()
    stop = None
    port = {}

    def server_thread():
        nonlocal stop

        async def run():
            nonlocal stop
            mock = MockLLMClient(script=[assistant("Paris")])
            op = Operator(
                options=OperatorOptions(enable_rest=True, api_port=0, llm_probe=False,
                                        verify_channel_credentials=False),
                llm_factory=MockLLMClientFactory(mock),
            )
            op.task_reconciler.requeue_delay = 0.02
            await op.start()
            while not op.rest_server.bound_port:
                await asyncio.sleep(0.01)
            port["p"] = op.rest_server.bound_port
            stop = asyncio.Event()
            started.set()
            await stop.wait()
            await op.stop()

        loop = asyncio.new_event_loop()
        threads_loop["loop"] = loop
        loop.run_until_complete(run())

    threads_loop = {}
    t = threading.Thread(target=server_thread, daemon=True)
    t.start()
    assert started.wait(10)
    server = f"http://127.0.0.1:{port['p']}"

    manifest = tmp_path / "m.yaml"
    manifest.write_text(GETTING_STARTED)
    assert cli_main(["--server", server, "apply", "-f", str(manifest)]) == 0
    assert cli_main(["--server", server, "get", "Agent"]) == 0
    assert cli_main(["--server", server, "get", "LLM", "gpt-4o", "-o", "yaml"]) == 0
    # the scripted mock answers the task created by `task create --follow`
    assert (
        cli_main(["--server", server, "task", "create", "my-assistant", "hi", "--follow"]) == 0
    )
    assert cli_main(["--server", server, "events"]) == 0
    # find the created task and show its conversation
    import httpx as _httpx
    tasks = _httpx.get(f"{server}/v1/tasks").json()
    done = [t for t in tasks if t["phase"] == "FinalAnswer"]
    assert cli_main(["--server", server, "task", "show", done[0]["name"]]) == 0
    assert cli_main(["--server", server, "task", "show", "ghost"]) == 1
    assert cli_main(["--server", server, "engine"]) == 0
    assert cli_main(["--server", server, "delete", "Task", "hello-world-1"]) == 0

    threads_loop["loop"].call_soon_threadsafe(stop.set)
    t.join(timeout=10)


async def test_engine_status_endpoint_unconfigured():
    async with RestHarness() as h:
        resp = await h.http.get(f"{h.base}/v1/engine")
        assert (await resp.json()) == {"configured": False}
