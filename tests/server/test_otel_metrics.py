"""OTLP metrics export (observability/otel_metrics.py — the reference's
meter provider, internal/otel/otel.go:58-80) against a live fake collector."""

import asyncio
import json

from aiohttp import web

from agentcontrolplane_tpu.observability.metrics import Registry
from agentcontrolplane_tpu.observability.otel_metrics import MetricsExporter


async def test_exporter_pushes_otlp_json():
    received: list[dict] = []

    async def collect(request: web.Request) -> web.Response:
        received.append(json.loads(await request.read()))
        return web.json_response({})

    app = web.Application()
    app.router.add_post("/v1/metrics", collect)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]

    try:
        reg = Registry()
        reg.counter_add("acp_reconcile_total", 3.0, {"controller": "task"}, help="reconciles")
        reg.gauge_set("acp_engine_active_slots", 5.0, help="slots")
        reg.observe("acp_engine_ttft_seconds", 0.25, help="ttft")
        reg.observe("acp_engine_ttft_seconds", 0.35)

        exporter = MetricsExporter(reg, endpoint=f"http://127.0.0.1:{port}")
        ok = await asyncio.to_thread(exporter.export_once)
        assert ok
        assert len(received) == 1
        doc = received[0]
        scope = doc["resourceMetrics"][0]["scopeMetrics"][0]
        by_name = {m["name"]: m for m in scope["metrics"]}
        ctr = by_name["acp_reconcile_total"]["sum"]
        assert ctr["isMonotonic"] and ctr["dataPoints"][0]["asDouble"] == 3.0
        assert ctr["dataPoints"][0]["attributes"] == [
            {"key": "controller", "value": {"stringValue": "task"}}
        ]
        assert by_name["acp_engine_active_slots"]["gauge"]["dataPoints"][0]["asDouble"] == 5.0
        summ = by_name["acp_engine_ttft_seconds"]["summary"]["dataPoints"][0]
        assert summ["count"] == "2"
        assert abs(summ["sum"] - 0.6) < 1e-9
        assert any(q["quantile"] == 0.5 for q in summ["quantileValues"])
    finally:
        await runner.cleanup()


async def test_exporter_noop_without_endpoint_and_graceful_on_refused():
    exporter = MetricsExporter(Registry(), endpoint="")
    exporter.start()  # no-op
    assert exporter._thread is None
    exporter.stop()

    dead = MetricsExporter(Registry(), endpoint="http://127.0.0.1:1")
    assert (await asyncio.to_thread(dead.export_once)) is False  # silent, no raise
