"""HTTPS/mTLS serving posture (server/rest.py TLS support).

Mirrors the reference's TLS-optioned servers fed by cert watchers
(acp/cmd/main.go:118-166) and its authn/authz-filtered metrics endpoint
(acp/cmd/main.go:167-206): cert+key => HTTPS; client CA => required client
certs; rotated cert files picked up without restart; bearer authn composes
with TLS.
"""

from __future__ import annotations

import asyncio
import datetime
import ssl

import aiohttp
import pytest

from agentcontrolplane_tpu.llmclient import MockLLMClient, MockLLMClientFactory
from agentcontrolplane_tpu.operator import Operator, OperatorOptions


def _make_cert(tmp_path, name: str, cn: str, issuer_key=None, issuer_cert=None,
               is_ca: bool = False):
    """Self-signed (or CA-signed) cert + key PEM files; returns paths and
    the (cert, key) objects for chaining."""
    pytest.importorskip("cryptography")  # needed only to mint the test certs
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    issuer_name = issuer_cert.subject if issuer_cert is not None else subject
    sign_key = issuer_key if issuer_key is not None else key
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(issuer_name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost"),
                                         x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]),
            critical=False,
        )
        .add_extension(
            x509.BasicConstraints(ca=is_ca, path_length=None), critical=True
        )
        .sign(sign_key, hashes.SHA256())
    )
    cert_path = tmp_path / f"{name}.crt"
    key_path = tmp_path / f"{name}.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return cert_path, key_path, cert, key


class TLSHarness:
    def __init__(self, tmp_path, **opts):
        self.operator = Operator(
            options=OperatorOptions(
                enable_rest=True,
                api_port=0,
                llm_probe=False,
                verify_channel_credentials=False,
                **opts,
            ),
            llm_factory=MockLLMClientFactory(MockLLMClient()),
        )
        self.store = self.operator.store

    async def __aenter__(self):
        await self.operator.start()
        for _ in range(200):
            if self.operator.rest_server.bound_port:
                break
            await asyncio.sleep(0.02)
        self.base = f"https://127.0.0.1:{self.operator.rest_server.bound_port}"
        return self

    async def __aexit__(self, *exc):
        await self.operator.stop()


def _client_ssl(ca_path, cert_path=None, key_path=None) -> ssl.SSLContext:
    ctx = ssl.create_default_context(cafile=str(ca_path))
    ctx.check_hostname = False  # the SAN covers 127.0.0.1, but keep tests lax
    if cert_path is not None:
        ctx.load_cert_chain(str(cert_path), str(key_path))
    return ctx


async def test_https_serving(tmp_path):
    cert, key, *_ = _make_cert(tmp_path, "server", "acp-tpu")
    async with TLSHarness(
        tmp_path, tls_cert_path=str(cert), tls_key_path=str(key)
    ) as h:
        async with aiohttp.ClientSession() as http:
            resp = await http.get(f"{h.base}/healthz", ssl=_client_ssl(cert))
            assert resp.status == 200
            assert (await resp.json())["status"] == "ok"
            # plaintext to the TLS port must fail the handshake, not serve
            with pytest.raises(aiohttp.ClientError):
                await http.get(h.base.replace("https", "http") + "/healthz")


async def test_https_with_bearer_token(tmp_path):
    """TLS composes with authn: the /metrics + API surface requires the
    token; health probes stay open (cmd/main.go:306-313 parity)."""
    cert, key, *_ = _make_cert(tmp_path, "server", "acp-tpu")
    async with TLSHarness(
        tmp_path,
        tls_cert_path=str(cert), tls_key_path=str(key), api_token="s3cret",
    ) as h:
        sslctx = _client_ssl(cert)
        async with aiohttp.ClientSession() as http:
            assert (await http.get(f"{h.base}/healthz", ssl=sslctx)).status == 200
            assert (await http.get(f"{h.base}/metrics", ssl=sslctx)).status == 401
            resp = await http.get(
                f"{h.base}/metrics", ssl=sslctx,
                headers={"Authorization": "Bearer s3cret"},
            )
            assert resp.status == 200


async def test_mtls_requires_client_cert(tmp_path):
    ca_cert, ca_key_path, ca_obj, ca_key = _make_cert(
        tmp_path, "ca", "acp-ca", is_ca=True
    )
    cert, key, *_ = _make_cert(tmp_path, "server", "acp-tpu")
    client_cert, client_key, *_ = _make_cert(
        tmp_path, "client", "acp-client", issuer_key=ca_key, issuer_cert=ca_obj
    )
    async with TLSHarness(
        tmp_path,
        tls_cert_path=str(cert),
        tls_key_path=str(key),
        tls_client_ca_path=str(ca_cert),
    ) as h:
        async with aiohttp.ClientSession() as http:
            # no client cert -> handshake rejected
            with pytest.raises(aiohttp.ClientError):
                await http.get(f"{h.base}/healthz", ssl=_client_ssl(cert))
            # CA-signed client cert -> served
            resp = await http.get(
                f"{h.base}/healthz",
                ssl=_client_ssl(cert, client_cert, client_key),
            )
            assert resp.status == 200


async def test_client_ca_rotation_revokes_old_ca(tmp_path, monkeypatch):
    """Rotating the client CA must REPLACE trust, not extend it: a fresh
    context is swapped into the listener, because load_verify_locations on
    a live SSLContext is additive and would keep trusting the rotated-out
    CA until restart."""
    monkeypatch.setenv("ACP_TLS_RELOAD_INTERVAL_S", "0.1")
    ca1_cert, _, ca1_obj, ca1_key = _make_cert(tmp_path, "ca1", "acp-ca1", is_ca=True)
    ca2_cert, _, ca2_obj, ca2_key = _make_cert(tmp_path, "ca2", "acp-ca2", is_ca=True)
    cert, key, *_ = _make_cert(tmp_path, "server", "acp-tpu")
    c1_cert, c1_key, *_ = _make_cert(
        tmp_path, "c1", "client-1", issuer_key=ca1_key, issuer_cert=ca1_obj
    )
    c2_cert, c2_key, *_ = _make_cert(
        tmp_path, "c2", "client-2", issuer_key=ca2_key, issuer_cert=ca2_obj
    )
    client_ca = tmp_path / "client-ca.pem"
    client_ca.write_bytes(ca1_cert.read_bytes())
    async with TLSHarness(
        tmp_path,
        tls_cert_path=str(cert),
        tls_key_path=str(key),
        tls_client_ca_path=str(client_ca),
    ) as h:
        async with aiohttp.ClientSession() as http:
            r = await http.get(
                f"{h.base}/healthz", ssl=_client_ssl(cert, c1_cert, c1_key)
            )
            assert r.status == 200

        client_ca.write_bytes(ca2_cert.read_bytes())  # rotate CA1 -> CA2

        ok2 = False
        for _ in range(100):  # wait for the reload tick to swap the listener
            async with aiohttp.ClientSession() as http:
                try:
                    r = await http.get(
                        f"{h.base}/healthz", ssl=_client_ssl(cert, c2_cert, c2_key)
                    )
                    ok2 = r.status == 200
                except aiohttp.ClientError:
                    ok2 = False
            if ok2:
                break
            await asyncio.sleep(0.1)
        assert ok2, "rotated-in client CA was never accepted"

        # the rotated-OUT CA must fail a FRESH handshake (new session = no
        # pooled connection to ride)
        async with aiohttp.ClientSession() as http:
            with pytest.raises(aiohttp.ClientError):
                await http.get(
                    f"{h.base}/healthz", ssl=_client_ssl(cert, c1_cert, c1_key)
                )


async def test_cert_rotation_without_restart(tmp_path, monkeypatch):
    """Cert-watcher parity: overwrite the cert/key files; new handshakes
    pick up the rotated chain without a server restart."""
    monkeypatch.setenv("ACP_TLS_RELOAD_INTERVAL_S", "0.1")
    cert, key, *_ = _make_cert(tmp_path, "server", "acp-old")
    async with TLSHarness(
        tmp_path, tls_cert_path=str(cert), tls_key_path=str(key)
    ) as h:
        async with aiohttp.ClientSession() as http:
            resp = await http.get(f"{h.base}/healthz", ssl=_client_ssl(cert))
            assert resp.status == 200

            # rotate in place (same paths, new keypair + CN)
            new_cert, new_key, *_ = _make_cert(tmp_path, "rotated", "acp-new")
            cert.write_bytes(new_cert.read_bytes())
            key.write_bytes(new_key.read_bytes())

            async def rotated() -> bool:
                try:
                    r = await http.get(
                        f"{h.base}/healthz", ssl=_client_ssl(new_cert)
                    )
                    return r.status == 200
                except aiohttp.ClientError:
                    return False  # old chain still served

            for _ in range(100):
                if await rotated():
                    break
                await asyncio.sleep(0.1)
            else:
                pytest.fail("rotated certificate was never served")
