"""``GET /v1/engine/trace`` and ``GET /v1/fleet/trace`` — the flight
recorder's anonymized replayable trace through the REST front door, the
503 posture when unconfigured, and the bearer-token gate."""

from __future__ import annotations

from types import SimpleNamespace

from agentcontrolplane_tpu.fleet import FleetRouter
from agentcontrolplane_tpu.kernel import Store
from agentcontrolplane_tpu.observability.flight import FlightRecorder
from agentcontrolplane_tpu.observability.trace_export import (
    TRACE_VERSION,
    validate_trace,
)

from .test_rest import RestHarness
from ..fleet.test_rest_fleet import FleetHarness, _StubEngine


def _recorded_engine() -> SimpleNamespace:
    """A stand-in engine whose flight recorder carries two finished
    requests — /v1/engine/trace only walks the recorder's declared
    cross-thread surface, so the trace path needs no TPU engine."""
    rec = FlightRecorder(enabled=True)
    for i, rid in enumerate(("ra", "rb")):
        rec.record("submit", rid=rid, prompt_tokens=10 + i, key=f"k{i}")
        rec.record("admit", rid=rid)
        rec.record("prefill_done", rid=rid)
        rec.finish(rid, "stop", tokens=3)
    return SimpleNamespace(flight=rec)


async def test_engine_trace_503_without_engine():
    async with RestHarness() as h:
        resp = await h.http.get(f"{h.base}/v1/engine/trace")
        assert resp.status == 503


async def test_engine_trace_serves_valid_anonymized_doc():
    h = RestHarness()
    h.operator.engine = _recorded_engine()
    async with h:
        resp = await h.http.get(f"{h.base}/v1/engine/trace")
        assert resp.status == 200
        doc = await resp.json()
        assert doc["version"] == TRACE_VERSION
        assert doc["anonymized"] is True
        assert validate_trace(doc) == []
        assert len(doc["requests"]) == 2
        assert {r["prompt_tokens"] for r in doc["requests"]} == {10, 11}


async def test_engine_trace_requires_token_when_configured():
    h = RestHarness(api_token="s3cret-trace")
    h.operator.engine = _recorded_engine()
    async with h:
        resp = await h.http.get(f"{h.base}/v1/engine/trace")
        assert resp.status == 401
        resp = await h.http.get(
            f"{h.base}/v1/engine/trace",
            headers={"Authorization": "Bearer s3cret-trace"},
        )
        assert resp.status == 200


async def test_fleet_trace_503_without_router():
    async with RestHarness() as h:
        resp = await h.http.get(f"{h.base}/v1/fleet/trace")
        assert resp.status == 503


async def test_fleet_trace_serves_stitched_doc():
    router = FleetRouter(store=Store(), heartbeat_interval=60.0)
    router.add_replica("r0", _StubEngine())
    router.add_replica("r1", _StubEngine())
    try:
        for i in range(3):
            router.submit(f"fleet trace req {i}").result(timeout=10)
        async with FleetHarness(fleet=router) as h:
            resp = await h.http.get(f"{h.base}/v1/fleet/trace")
            assert resp.status == 200
            doc = await resp.json()
            assert doc["source"] == "fleet"
            assert validate_trace(doc) == []
            assert len(doc["requests"]) == 3
            # stub replicas have no recorders: every linked engine leg is
            # reported missing rather than silently dropped
            assert doc["flight"]["missing_legs"] >= 1
    finally:
        router.stop()
