"""REST API tests — httptest-style against the live aiohttp server
(reference: internal/server/server_test.go, 1,641 LoC)."""

import asyncio
import json

import aiohttp
import pytest

from agentcontrolplane_tpu.kernel import wait_for
from agentcontrolplane_tpu.llmclient import (
    MockLLMClient,
    MockLLMClientFactory,
    assistant,
)
from agentcontrolplane_tpu.operator import Operator, OperatorOptions

from ..fixtures import make_agent, make_llm, make_task


class RestHarness:
    def __init__(self, **opts):
        self.mock = MockLLMClient()
        self.operator = Operator(
            options=OperatorOptions(
                enable_rest=True,
                api_port=0,  # ephemeral
                llm_probe=False,
                verify_channel_credentials=False,
                **opts,
            ),
            llm_factory=MockLLMClientFactory(self.mock),
        )
        self.operator.task_reconciler.requeue_delay = 0.02
        self.operator.toolcall_reconciler.poll_interval = 0.02
        self.store = self.operator.store

    async def __aenter__(self):
        await self.operator.start()
        for _ in range(100):
            if self.operator.rest_server.bound_port:
                break
            await asyncio.sleep(0.02)
        self.base = f"http://127.0.0.1:{self.operator.rest_server.bound_port}"
        self.http = aiohttp.ClientSession()
        return self

    async def __aexit__(self, *exc):
        await self.http.close()
        await self.operator.stop()


async def test_create_task_and_poll_to_completion():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        h.mock.script.append(assistant("Paris"))
        resp = await h.http.post(
            f"{h.base}/v1/tasks", json={"agentName": "helper", "userMessage": "capital of france?"}
        )
        assert resp.status == 201
        body = await resp.json()
        assert body["name"].startswith("helper-task-")
        assert body["userMsgPreview"] == ""  # not yet reconciled

        task_name = body["name"]
        await wait_for(
            h.store, "Task", task_name, "default",
            lambda t: t.status.phase == "FinalAnswer", timeout=10,
        )
        resp = await h.http.get(f"{h.base}/v1/tasks/{task_name}")
        got = await resp.json()
        assert got["phase"] == "FinalAnswer"
        assert got["output"] == "Paris"
        assert [m["role"] for m in got["contextWindow"]] == ["system", "user", "assistant"]


async def test_create_task_missing_agent_404():
    async with RestHarness() as h:
        resp = await h.http.post(
            f"{h.base}/v1/tasks", json={"agentName": "ghost", "userMessage": "hi"}
        )
        assert resp.status == 404


async def test_create_task_strict_decode_rejects_unknown_fields():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        resp = await h.http.post(
            f"{h.base}/v1/tasks",
            json={"agentName": "helper", "userMessage": "hi", "bogusField": 1},
        )
        assert resp.status == 400
        assert "unknown fields" in (await resp.json())["error"]


async def test_create_task_requires_exactly_one_input():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        resp = await h.http.post(f"{h.base}/v1/tasks", json={"agentName": "helper"})
        assert resp.status == 400
        resp = await h.http.post(
            f"{h.base}/v1/tasks",
            json={
                "agentName": "helper",
                "userMessage": "x",
                "contextWindow": [{"role": "user", "content": "y"}],
            },
        )
        assert resp.status == 400


async def test_create_agent_creates_llm_and_secret():
    async with RestHarness() as h:
        resp = await h.http.post(
            f"{h.base}/v1/agents",
            json={
                "name": "writer",
                "systemPrompt": "you write",
                "llm": {"provider": "mock", "model": "m", "apiKey": "sk-123"},
            },
        )
        assert resp.status == 201
        assert h.store.try_get("Agent", "writer") is not None
        assert h.store.try_get("LLM", "writer-llm") is not None
        secret = h.store.try_get("Secret", "writer-llm-key")
        assert secret.spec.data == {"api-key": "sk-123"}

        # duplicate -> 409, and no orphaned extra objects
        resp = await h.http.post(
            f"{h.base}/v1/agents",
            json={"name": "writer", "systemPrompt": "x", "llm": {"provider": "mock"}},
        )
        assert resp.status == 409

        resp = await h.http.get(f"{h.base}/v1/agents/writer")
        body = await resp.json()
        assert body["llmRef"] == "writer-llm"


async def test_v1beta3_event_fabricates_channel_and_task():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="support")
        h.mock.script.append(assistant("I'll help with that"))
        resp = await h.http.post(
            f"{h.base}/v1/beta3/events",
            json={
                "type": "agent_slack.received",
                "agentName": "support",
                "channelApiKey": "xoxb-token",
                "event": {
                    "message": "help me",
                    "thread_ts": "171717.42",
                    "channel_id": "C0AAAAAAAAA",
                    "event_id": "ev12345",
                },
            },
        )
        assert resp.status == 201
        body = await resp.json()
        assert body["channel"] == "v1beta3-channel-ev12345"
        task = h.store.get("Task", body["taskName"])
        assert task.metadata.labels["acp.tpu/v1beta3"] == "true"
        assert task.spec.thread_id == "171717.42"
        assert task.spec.channel_token_from.name == "v1beta3-token-ev12345"
        channel = h.store.get("ContactChannel", "v1beta3-channel-ev12345")
        assert channel.status.ready

        # v1beta3 task completes by delivering the answer through a
        # respond_to_human tool call against the in-tree human backend
        task = await wait_for(
            h.store, "Task", body["taskName"], "default",
            lambda t: t.status.phase in ("FinalAnswer", "Failed"), timeout=10,
        )
        assert task.status.phase == "FinalAnswer"
        assert task.status.output == "I'll help with that"


async def test_approvals_endpoint_roundtrip():
    async with RestHarness() as h:
        backend = h.operator.human_backend
        client = h.operator.hl_factory.create_client("")
        from agentcontrolplane_tpu.humanlayer import FunctionCallSpec

        call_id = await client.request_approval(
            "run1", "call-abc", FunctionCallSpec(fn="web__fetch", kwargs={"url": "x"})
        )
        resp = await h.http.get(f"{h.base}/v1/approvals")
        pending = await resp.json()
        assert [p["callId"] for p in pending] == [call_id]

        resp = await h.http.post(f"{h.base}/v1/approvals/{call_id}/approve?comment=ok")
        assert resp.status == 200
        status = await client.get_function_call_status(call_id)
        assert status.approved is True and status.comment == "ok"

        resp = await h.http.get(f"{h.base}/v1/approvals")
        assert await resp.json() == []


async def test_metrics_and_health():
    async with RestHarness() as h:
        resp = await h.http.get(f"{h.base}/healthz")
        assert (await resp.json())["status"] == "ok"
        resp = await h.http.get(f"{h.base}/metrics")
        assert resp.status == 200


async def test_metrics_phase_gauges_track_and_zero_out():
    """acp_objects{kind,phase} is computed at scrape time and drained
    series drop to 0 instead of freezing at their last count (dashboard
    'Tasks by phase' panel depends on this)."""
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        make_task(h.store, name="t1", agent="helper", user_message="hi")
        text = await (await h.http.get(f"{h.base}/metrics")).text()
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("acp_objects{") and 'kind="Task"' in ln
        )
        assert line.endswith(" 1.0")
        h.store.delete("Task", "t1")
        text = await (await h.http.get(f"{h.base}/metrics")).text()
        line = next(
            ln for ln in text.splitlines()
            if ln.startswith("acp_objects{") and 'kind="Task"' in ln
        )
        assert line.endswith(" 0.0")  # zeroed, not stale
        # ...and DROPPED on the next scrape (ADVICE r3: re-emitting every
        # series ever observed is unbounded gauge cardinality under churn)
        text = await (await h.http.get(f"{h.base}/metrics")).text()
        assert not any(
            ln.startswith("acp_objects{") and 'kind="Task"' in ln
            for ln in text.splitlines()
        )


async def test_update_agent_patch():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        resp = await h.http.patch(
            f"{h.base}/v1/agents/helper",
            json={"systemPrompt": "new prompt", "description": "d2"},
        )
        assert resp.status == 200
        agent = h.store.get("Agent", "helper")
        assert agent.spec.system == "new prompt"
        assert agent.spec.description == "d2"
        assert agent.metadata.generation == 2  # spec change bumped generation

        resp = await h.http.patch(f"{h.base}/v1/agents/helper", json={"systemPrompt": ""})
        assert resp.status == 400
        resp = await h.http.patch(f"{h.base}/v1/agents/helper", json={"bogus": 1})
        assert resp.status == 400
        resp = await h.http.patch(f"{h.base}/v1/agents/ghost", json={"description": "x"})
        assert resp.status == 404


async def test_delete_task_endpoint():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        h.mock.script.append(assistant("hi"))
        resp = await h.http.post(
            f"{h.base}/v1/tasks", json={"agentName": "helper", "userMessage": "x"}
        )
        name = (await resp.json())["name"]
        resp = await h.http.delete(f"{h.base}/v1/tasks/{name}")
        assert resp.status == 200
        assert h.store.try_get("Task", name) is None
        resp = await h.http.delete(f"{h.base}/v1/tasks/{name}")
        assert resp.status == 404


async def test_update_agent_rejects_bad_types():
    async with RestHarness() as h:
        make_llm(h.store)
        make_agent(h.store, name="helper")
        for bad in (
            {"systemPrompt": 123},
            {"mcpServers": "tools"},
            {"mcpServers": [5]},
            {"subAgents": [""]},
        ):
            resp = await h.http.patch(f"{h.base}/v1/agents/helper", json=bad)
            assert resp.status == 400, bad
        # agent untouched and still readable
        agent = h.store.get("Agent", "helper")
        assert agent.spec.system == "you are a helpful assistant"


async def test_chat_completions_endpoint():
    """OpenAI-compatible front door straight into the TPU engine."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
    )
    eng.start()
    try:
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            resp = await h.http.post(
                f"{h.base}/v1/chat/completions",
                json={
                    "model": "tiny",
                    "messages": [
                        {"role": "system", "content": "s"},
                        {"role": "user", "content": "hello"},
                    ],
                    "max_tokens": 8,
                    "temperature": 0,
                },
            )
            assert resp.status == 200
            body = await resp.json()
            assert body["object"] == "chat.completion"
            assert body["id"].startswith("chatcmpl-")
            assert body["usage"]["completion_tokens"] >= 1
            assert body["usage"]["total_tokens"] > body["usage"]["completion_tokens"]
            assert body["choices"][0]["finish_reason"] in ("stop", "tool_calls", "length")

            # multi-turn tool history with assistant tool_calls roundtrips
            resp = await h.http.post(
                f"{h.base}/v1/chat/completions",
                json={
                    "messages": [
                        {"role": "user", "content": "fetch x"},
                        {"role": "assistant", "content": None, "tool_calls": [
                            {"id": "call_1", "type": "function",
                             "function": {"name": "web__fetch", "arguments": "{}"}}]},
                        {"role": "tool", "content": "result", "tool_call_id": "call_1"},
                    ],
                    "tools": [{"type": "function", "function": {"name": "web__fetch"}}],
                    "max_tokens": 6, "temperature": 0,
                },
            )
            assert resp.status == 200

            # probes: malformed body; no messages; bad tools; non-object body
            resp = await h.http.post(f"{h.base}/v1/chat/completions", data=b"{broken")
            assert resp.status == 400
            resp = await h.http.post(f"{h.base}/v1/chat/completions", json={"model": "x"})
            assert resp.status == 400
            resp = await h.http.post(
                f"{h.base}/v1/chat/completions",
                json={"messages": [{"role": "user", "content": "x"}], "tools": [{}]},
            )
            assert resp.status == 400
            resp = await h.http.post(f"{h.base}/v1/chat/completions", json=[1, 2])
            assert resp.status == 400
            # assistant history with unparseable tool_calls arguments is
            # malformed CLIENT input: 400, not an unhandled 500
            resp = await h.http.post(
                f"{h.base}/v1/chat/completions",
                json={
                    "messages": [
                        {"role": "user", "content": "x"},
                        {"role": "assistant", "content": None, "tool_calls": [
                            {"id": "c1", "type": "function",
                             "function": {"name": "f", "arguments": "{broken"}}]},
                        {"role": "tool", "content": "r", "tool_call_id": "c1"},
                    ],
                },
            )
            assert resp.status == 400
    finally:
        eng.stop()


async def test_chat_completions_without_engine_503():
    async with RestHarness() as h:
        resp = await h.http.post(
            f"{h.base}/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
        )
        assert resp.status == 503


async def test_secret_data_redacted_on_resource_endpoints():
    """Generic resource GET/LIST must never serve Secret payloads (the
    reference never exposes Secrets over REST at all; server.go:132-156)."""
    from agentcontrolplane_tpu.api import ObjectMeta
    from agentcontrolplane_tpu.api.resources import Secret, SecretSpec

    async with RestHarness() as h:
        h.store.create(
            Secret(
                metadata=ObjectMeta(name="llm-key"),
                spec=SecretSpec(data={"api-key": "sk-super-secret"}),
            )
        )
        resp = await h.http.get(f"{h.base}/v1/resources/Secret/llm-key")
        assert resp.status == 200
        text = await resp.text()
        assert "sk-super-secret" not in text
        assert (await h.http.get(f"{h.base}/v1/resources/Secret/llm-key")).status == 200
        resp = await h.http.get(f"{h.base}/v1/resources/Secret")
        assert "sk-super-secret" not in await resp.text()
        body = await (await h.http.get(f"{h.base}/v1/resources/Secret/llm-key")).json()
        assert body["spec"]["data"] == {"api-key": "<redacted>"}
        # the controllers still read the real value from the store
        assert h.store.get("Secret", "llm-key").spec.data["api-key"] == "sk-super-secret"


async def test_bearer_token_auth():
    """With api_token configured every route except health probes requires
    Authorization: Bearer <token> (reference authn posture, cmd/main.go:167-206)."""
    h = RestHarness(api_token="t0ps3cret")
    async with h:
        assert (await h.http.get(f"{h.base}/v1/tasks")).status == 401
        assert (await h.http.get(f"{h.base}/healthz")).status == 200
        ok = await h.http.get(
            f"{h.base}/v1/tasks", headers={"Authorization": "Bearer t0ps3cret"}
        )
        assert ok.status == 200
        bad = await h.http.get(
            f"{h.base}/v1/tasks", headers={"Authorization": "Bearer wrong"}
        )
        assert bad.status == 401


async def test_chat_completions_streaming_sse():
    """stream:true — OpenAI chat.completion.chunk SSE: role chunk, content
    deltas whose concatenation equals the non-streamed text, finish chunk,
    [DONE]."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256), decode_block_size=4,
    )
    eng.start()
    try:
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            payload = {
                "model": "tiny",
                "messages": [{"role": "user", "content": "stream me"}],
                "max_tokens": 12,
                "temperature": 0,
            }
            # non-streamed reference text
            ref = await (await h.http.post(
                f"{h.base}/v1/chat/completions", json=payload
            )).json()
            ref_text = ref["choices"][0]["message"]["content"] or ""

            resp = await h.http.post(
                f"{h.base}/v1/chat/completions", json={**payload, "stream": True}
            )
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/event-stream")
            raw = (await resp.read()).decode()
            events = [
                json.loads(line[len("data: "):])
                for line in raw.splitlines()
                if line.startswith("data: ") and line != "data: [DONE]"
            ]
            assert raw.rstrip().endswith("data: [DONE]")
            assert events[0]["choices"][0]["delta"].get("role") == "assistant"
            assert all(e["object"] == "chat.completion.chunk" for e in events)
            content = "".join(
                e["choices"][0]["delta"].get("content") or "" for e in events
            )
            assert content == ref_text
            finishes = [e["choices"][0]["finish_reason"] for e in events]
            assert finishes[-1] in ("stop", "length")
    finally:
        eng.stop()


async def test_list_models_endpoint():
    async with RestHarness() as h:
        make_llm(h.store)
        resp = await h.http.get(f"{h.base}/v1/models")
        body = await resp.json()
        assert resp.status == 200 and body["object"] == "list"
        ids = [m["id"] for m in body["data"]]
        assert "test-llm" in ids  # no engine configured in this harness


async def test_chat_completions_sheds_503_with_retry_after_at_queue_cap():
    """Bounded admission end to end: with the engine's admission queue at
    its cap, the generate endpoint answers 503 + Retry-After immediately —
    a client is never parked on an unbounded queue wait. An expired
    queued-deadline (timeout_s) likewise fails fast with 504."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
        max_queue=1,
    )
    eng.start()
    try:
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            body = {
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4, "temperature": 0,
            }
            with eng.hold_admission():
                # filler occupies the whole queue (cap 1) while held
                filler = eng.submit("filler", SamplingParams(max_tokens=4))
                resp = await h.http.post(f"{h.base}/v1/chat/completions", json=body)
                assert resp.status == 503
                assert int(resp.headers["Retry-After"]) >= 1
                # streaming sheds the same way, BEFORE the SSE preamble
                resp = await h.http.post(
                    f"{h.base}/v1/chat/completions", json={**body, "stream": True}
                )
                assert resp.status == 503
                assert int(resp.headers["Retry-After"]) >= 1
            assert filler.result(timeout=120).finish_reason in ("stop", "length")
            # released: the endpoint serves normally again
            resp = await h.http.post(f"{h.base}/v1/chat/completions", json=body)
            assert resp.status == 200
    finally:
        eng.stop()


async def test_chat_completions_timeout_s_expires_queued_request_fast():
    import dataclasses
    import time

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
    )
    eng.start()
    try:
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            with eng.hold_admission():
                t0 = time.monotonic()
                resp = await h.http.post(
                    f"{h.base}/v1/chat/completions",
                    json={
                        "messages": [{"role": "user", "content": "hi"}],
                        "max_tokens": 4, "temperature": 0, "timeout_s": 1,
                    },
                )
                # expired while queued (held admission): fail fast — the
                # per-request deadline, not the old hard-coded 600s
                assert resp.status == 504
                assert time.monotonic() - t0 < 30
    finally:
        eng.stop()


async def test_engine_status_exposes_decode_efficiency_and_spec_block():
    """/v1/engine must surface tokens_per_decode_step and the speculative-
    decoding stats block (ISSUE 5 acceptance: visible decode efficiency)."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
        spec_len=4,
    )
    eng.start()
    try:
        eng.generate("abcabcabcabc", SamplingParams(temperature=0.0, max_tokens=12))
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            resp = await h.http.get(f"{h.base}/v1/engine")
            doc = await resp.json()
            assert doc["configured"] is True
            assert doc["tokens_per_decode_step"] > 0
            spec = doc["spec"]
            assert spec["enabled"] is True and spec["spec_len"] == 4
            for key in ("proposed", "accepted", "acceptance_rate", "verify_dispatches"):
                assert key in spec
            # KV memory tiers (ISSUE 11): the `memory` block must ride
            # /v1/engine — host-tier occupancy + dedup payoff for ops
            mem = doc["memory"]
            assert mem["host_kv"]["enabled"] is False  # knob off here
            assert mem["host_kv"]["used_bytes"] == 0
            for key in ("swap_outs", "swap_ins", "max_bytes", "entries"):
                assert key in mem["host_kv"]
            assert mem["prefix_dedup"]["enabled"] is False  # slot layout
            for key in ("shares", "shared_pages"):
                assert key in mem["prefix_dedup"]
            # the scrape-time gauges ride /metrics too
            h.operator.options.engine = eng
            text = await (await h.http.get(f"{h.base}/metrics")).text()
            assert "acp_engine_tokens_per_decode_step" in text
            assert "acp_engine_host_kv_bytes" in text
            assert "acp_engine_prefix_shared_pages" in text
    finally:
        eng.stop()


async def test_chat_completions_sse_streams_early_tool_call_deltas():
    """Overlapped tool execution over the OpenAI SSE wire: with tools, a
    tool_calls delta chunk is emitted the moment the streamed call's
    arguments close — BEFORE the finish chunk — and accumulating the
    deltas by index yields exactly the non-streamed response's call set
    (names + arguments; ids are per-request randoms)."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=512, prefill_buckets=(256, 512), decode_block_size=4,
    )
    eng.start()
    try:
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            # tool_choice "required" teacher-forces the call envelope +
            # grammar constraint, so a random-weights model deterministically
            # produces a parseable call over the wire
            payload = {
                "model": "tiny",
                "messages": [{"role": "user", "content": "call the tool"}],
                "tools": [
                    {"function": {"name": "svc__lookup", "description": "", "parameters": {}}}
                ],
                "tool_choice": "required",
                "max_tokens": 24,
                "temperature": 0,
            }
            ref = await (await h.http.post(
                f"{h.base}/v1/chat/completions", json=payload
            )).json()
            ref_msg = ref["choices"][0]["message"]
            assert ref_msg.get("tool_calls"), ref_msg  # forced call landed

            resp = await h.http.post(
                f"{h.base}/v1/chat/completions", json={**payload, "stream": True}
            )
            assert resp.status == 200
            raw = (await resp.read()).decode()
            events = [
                json.loads(line[len("data: "):])
                for line in raw.splitlines()
                if line.startswith("data: ") and line != "data: [DONE]"
            ]
            deltas = [e["choices"][0]["delta"] for e in events]
            # the early delta precedes the finish chunk
            first_tc = next(i for i, d in enumerate(deltas) if d.get("tool_calls"))
            finish_idx = next(
                i for i, e in enumerate(events)
                if e["choices"][0]["finish_reason"] is not None
            )
            assert first_tc < finish_idx
            assert events[finish_idx]["choices"][0]["finish_reason"] == "tool_calls"
            # accumulate tool_calls deltas by index -> the non-streamed set
            acc: dict[int, dict] = {}
            for d in deltas:
                for tc in d.get("tool_calls") or []:
                    acc[tc["index"]] = tc
            assert [
                (acc[i]["function"]["name"], acc[i]["function"]["arguments"])
                for i in sorted(acc)
            ] == [
                (tc["function"]["name"], tc["function"]["arguments"])
                for tc in ref_msg["tool_calls"]
            ]
            # buffer mode: raw tool-call JSON never leaks as content deltas
            assert not any(d.get("content") for d in deltas)
    finally:
        eng.stop()


async def test_flight_recorder_endpoints_and_auth():
    """ISSUE 10: GET /v1/engine/flight and /v1/requests/{id}/timeline —
    token-authed introspection over the engine flight recorder, with the
    timeline's phase attribution summing to ~end-to-end latency."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
    )
    eng.start()
    try:
        fut = eng.submit("flight over rest", SamplingParams(temperature=0.0, max_tokens=6))
        fut.result(timeout=60)
        rid = fut.rid
        for _ in range(100):
            doc = eng.flight.timeline_doc(rid)
            if doc and any(e["kind"] == "finish" for e in doc["events"]):
                break
            await asyncio.sleep(0.02)
        h = RestHarness(api_token="sekret")
        h.operator.engine = eng
        async with h:
            # token required (not a health route)
            resp = await h.http.get(f"{h.base}/v1/engine/flight")
            assert resp.status == 401
            hdr = {"Authorization": "Bearer sekret"}
            resp = await h.http.get(f"{h.base}/v1/engine/flight", headers=hdr)
            assert resp.status == 200
            flight = await resp.json()
            assert flight["enabled"] is True and flight["window_events"] > 0
            assert rid in flight["request_ids"]
            kinds = {e["kind"] for e in flight["events"]}
            assert {"submit", "admit", "prefill_done", "finish"} <= kinds
            # last-N + kind filters
            resp = await h.http.get(
                f"{h.base}/v1/engine/flight?last=1&kind=finish", headers=hdr
            )
            filtered = (await resp.json())["events"]
            assert len(filtered) == 1 and filtered[0]["kind"] == "finish"
            resp = await h.http.get(
                f"{h.base}/v1/engine/flight?last=bogus", headers=hdr
            )
            assert resp.status == 400
            # per-request timeline with phase attribution
            resp = await h.http.get(f"{h.base}/v1/requests/{rid}/timeline", headers=hdr)
            assert resp.status == 200
            tl = await resp.json()
            assert tl["request_id"] == rid
            assert [e["kind"] for e in tl["events"]][0] == "submit"
            assert all(
                a["seq"] < b["seq"]
                for a, b in zip(tl["events"], tl["events"][1:])
            )
            summed = sum(
                v for k, v in tl["phases"].items() if k != "tool_overlap_hidden"
            )
            assert abs(summed - tl["total_s"]) < 0.05
            resp = await h.http.get(f"{h.base}/v1/requests/nope/timeline", headers=hdr)
            assert resp.status == 404
    finally:
        eng.stop()


async def test_flight_endpoints_503_without_engine():
    async with RestHarness() as h:
        assert (await h.http.get(f"{h.base}/v1/engine/flight")).status == 503
        assert (await h.http.get(f"{h.base}/v1/requests/x/timeline")).status == 503


async def test_cli_timeline_against_live_server(capsys):
    """`acp-tpu timeline` (no arg: the window; with a rid: the lifecycle +
    phase table) against a live server with a tiny engine."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.cli import main as cli_main
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
    )
    eng.start()
    try:
        fut = eng.submit("cli timeline drive", SamplingParams(temperature=0.0, max_tokens=6))
        fut.result(timeout=60)
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            # blocking httpx must not run on the serving loop
            rc = await asyncio.to_thread(cli_main, ["--server", h.base, "timeline"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "flight recorder:" in out and fut.rid in out
            rc = await asyncio.to_thread(
                cli_main, ["--server", h.base, "timeline", fut.rid]
            )
            assert rc == 0
            out = capsys.readouterr().out
            assert f"request {fut.rid}" in out
            assert "prefill_done" in out and "phases" in out
            assert "decode" in out
            rc = await asyncio.to_thread(
                cli_main, ["--server", h.base, "timeline", "ghost"]
            )
            assert rc == 1
    finally:
        eng.stop()


async def test_perf_endpoint_and_cli(capsys):
    """ISSUE 12: /v1/engine/perf serves the compute efficiency observatory
    (per-program dispatch telemetry + cold compiles + goodput ledger) and
    `acp-tpu perf` renders it."""
    import dataclasses

    import jax

    from agentcontrolplane_tpu.cli import main as cli_main
    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=2, max_ctx=256, prefill_buckets=(128, 256),
    )
    eng.start()
    try:
        eng.generate("perf drive", SamplingParams(temperature=0.0, max_tokens=6))
        h = RestHarness()
        h.operator.engine = eng
        async with h:
            resp = await h.http.get(f"{h.base}/v1/engine/perf")
            assert resp.status == 200
            doc = await resp.json()
            assert doc["configured"] is True and doc["enabled"] is True
            assert any(k.startswith("prefill[") for k in doc["programs"])
            g = doc["goodput"]
            assert g["computed"] == g["goodput"] + sum(g["waste"].values())
            assert "cold_compiles" in doc
            rc = await asyncio.to_thread(cli_main, ["--server", h.base, "perf"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "goodput:" in out and "PROGRAM" in out
            rc = await asyncio.to_thread(
                cli_main, ["--server", h.base, "perf", "--json"]
            )
            assert rc == 0
            assert "programs" in capsys.readouterr().out
    finally:
        eng.stop()


async def test_perf_endpoint_503_without_engine():
    async with RestHarness() as h:
        assert (await h.http.get(f"{h.base}/v1/engine/perf")).status == 503


async def test_scrape_refresh_gauges_agree_with_engine_stats():
    """Satellite (ISSUE 12): every engine-side gauge the scrape path
    refreshes — the memory block (PR 11), the scheduler block (PR 7), and
    the new perf block — must agree with Engine.stats() after activity.
    Catches publisher/scrape drift: a gauge whose scrape-time refresh
    reads a different field than stats() serves would silently fork the
    dashboard from the API."""
    import dataclasses
    import re as _re

    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(PRESETS["tiny"], vocab_size=512, n_kv_heads=2)
    eng = Engine(
        config=cfg, tokenizer=ByteTokenizer(),
        mesh=make_mesh({"tp": 2}, devices=jax.devices()[:2]),
        max_slots=4, max_ctx=64, prefill_buckets=(32, 64),
        decode_block_size=4, kv_layout="paged", page_size=8,
        prefill_chunk=16, host_kv_bytes=1 << 22, spec_len=4,
    )
    eng.start()
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        futs = [eng.submit(f"scrape drift {i} " * 2, sp) for i in range(4)]
        for f in futs:
            f.result(timeout=120)
        # idle engine: stats() is stable across the scrape and the compare
        h = RestHarness()
        h.operator.engine = eng
        h.operator.options.engine = eng  # the scrape path reads options
        async with h:
            text = await (await h.http.get(f"{h.base}/metrics")).text()
            s = eng.stats()

            def gauge(name: str) -> float:
                m = _re.search(rf"^{name} (\S+)$", text, _re.M)
                assert m is not None, f"{name} missing from the scrape"
                return float(m.group(1))

            # scheduler block (PR 7)
            assert gauge("acp_engine_active_slots") == s["active_slots"]
            assert gauge("acp_engine_waiting_requests") == s["waiting"]
            assert gauge("acp_engine_prefilling_slots") == s["prefilling_slots"]
            assert gauge("acp_engine_tokens_per_decode_step") == pytest.approx(
                s["tokens_per_decode_step"]
            )
            assert gauge("acp_engine_token_budget_utilization") == pytest.approx(
                s["scheduler"]["budget_utilization_last"]
            )
            # memory block (PR 11)
            assert gauge("acp_engine_host_kv_bytes") == s["memory"]["host_kv"]["used_bytes"]
            assert gauge("acp_engine_prefix_shared_pages") == s["memory"][
                "prefix_dedup"
            ]["shared_pages"]
            # perf block (this PR)
            assert gauge("acp_engine_goodput_ratio") == pytest.approx(
                s["perf"]["goodput"]["ratio"], abs=1e-3
            )
    finally:
        eng.stop()
