"""Back-compat shim: the builder fixtures live in the package now
(``agentcontrolplane_tpu.testing``) so ``bench.py`` and the benchmarks can
run from a container image that ships without ``tests/`` (VERDICT r3 weak #7).
"""

from agentcontrolplane_tpu.testing import *  # noqa: F401,F403
from agentcontrolplane_tpu.testing import (  # noqa: F401
    make_agent,
    make_contactchannel,
    make_llm,
    make_mcpserver,
    make_secret,
    make_task,
    make_toolcall,
    setup_with_status,
    teardown,
)
