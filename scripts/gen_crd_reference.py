"""Generate docs/crd-reference.md from the pydantic API models.

Counterpart of the reference's hand-written acp/docs/crd-reference.md, but
generated so it CANNOT drift from the code: tests/test_docs_reference.py
regenerates it and fails if the committed file differs.

    python scripts/gen_crd_reference.py > docs/crd-reference.md
"""

from __future__ import annotations

import os
import sys
import types
import typing

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from agentcontrolplane_tpu.api import resources as R  # noqa: E402
from agentcontrolplane_tpu.api.meta import APIModel  # noqa: E402

KINDS = [
    ("LLM", R.LLMSpec, R.LLMStatus),
    ("Agent", R.AgentSpec, R.AgentStatus),
    ("Task", R.TaskSpec, R.TaskStatus),
    ("ToolCall", R.ToolCallSpec, R.ToolCallStatus),
    ("MCPServer", R.MCPServerSpec, R.MCPServerStatus),
    ("ContactChannel", R.ContactChannelSpec, R.ContactChannelStatus),
    ("Secret", R.SecretSpec, None),
]


def _type_name(tp) -> str:
    origin = typing.get_origin(tp)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        inner = " | ".join(_type_name(a) for a in args)
        return inner
    if origin in (list, tuple):
        args = typing.get_args(tp)
        return f"[{_type_name(args[0])}]" if args else "[...]"
    if origin is dict:
        k, v = typing.get_args(tp) or (str, str)
        return f"{{{_type_name(k)}: {_type_name(v)}}}"
    if origin is typing.Literal:
        return " \\| ".join(repr(a) for a in typing.get_args(tp))
    if isinstance(tp, type):
        if issubclass(tp, APIModel):
            return f"[{tp.__name__}](#{tp.__name__.lower()})"
        return tp.__name__
    return str(tp).replace("typing.", "")


def _default(field) -> str:
    from pydantic_core import PydanticUndefined

    if field.default is PydanticUndefined:
        if field.default_factory is not None:
            return "`{}`" if "dict" in repr(field.default_factory) else "(factory)"
        return "**required**"
    d = field.default
    if d is None:
        return "`null`"
    return f"`{d!r}`".replace("'", '"')


def _rows(model) -> list[str]:
    out = []
    for name, field in model.model_fields.items():
        camel = field.alias or name
        desc = (field.description or "").replace("\n", " ")
        out.append(
            f"| `{camel}` | {_type_name(field.annotation)} | {_default(field)} | {desc} |"
        )
    return out


def _submodels(model, seen) -> list:
    found = []

    def visit(tp):
        origin = typing.get_origin(tp)
        if origin is not None:
            for a in typing.get_args(tp):
                visit(a)
            return
        if isinstance(tp, type) and issubclass(tp, APIModel) and tp not in seen:
            seen.add(tp)
            found.append(tp)
            for f in tp.model_fields.values():
                visit(f.annotation)

    for f in model.model_fields.values():
        visit(f.annotation)
    return found


def main() -> None:
    print("# API reference (generated)")
    print()
    print("Field-by-field reference for every kind, generated from the")
    print("pydantic models in `api/resources.py` by")
    print("`scripts/gen_crd_reference.py` (lockstep-pinned by")
    print("`tests/test_docs_reference.py` — regenerate after API changes).")
    print("Manifests accept both camelCase (shown) and snake_case field")
    print("names. Counterpart of the reference's `acp/docs/crd-reference.md`.")
    seen: set = set()
    sub_queue: list = []
    for kind, spec, status in KINDS:
        print(f"\n## {kind}\n")
        print("### spec\n")
        print("| field | type | default | notes |")
        print("|---|---|---|---|")
        for row in _rows(spec):
            print(row)
        sub_queue += _submodels(spec, seen)
        if status is not None:
            print("\n### status\n")
            print("| field | type | default | notes |")
            print("|---|---|---|---|")
            for row in _rows(status):
                print(row)
            sub_queue += _submodels(status, seen)
    if sub_queue:
        print("\n## Shared types\n")
        for tp in sub_queue:
            print(f"\n### {tp.__name__}\n")
            print("| field | type | default | notes |")
            print("|---|---|---|---|")
            for row in _rows(tp):
                print(row)


if __name__ == "__main__":
    main()
