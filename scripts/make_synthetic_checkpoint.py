#!/usr/bin/env python
"""Generate a random-weight HF-format checkpoint shaped like a real one.

Closes the no-egress verification gap: serve a FULL-SIZE Llama-3-8B-shaped
checkpoint through `acp-tpu run --tpu-checkpoint` (load + int8 quantize +
shard) without downloading weights.

  python scripts/make_synthetic_checkpoint.py --preset llama3-8b --out /tmp/synth8b
  acp-tpu run --tpu-checkpoint /tmp/synth8b --tpu-quantize int8
"""

from __future__ import annotations

import argparse
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama3-8b")
    ap.add_argument("--out", required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shard-bytes", type=int, default=1 << 30)
    args = ap.parse_args()

    from agentcontrolplane_tpu.engine.weights import write_synthetic_checkpoint
    from agentcontrolplane_tpu.models.llama import PRESETS

    t0 = time.monotonic()
    total = write_synthetic_checkpoint(
        args.out, PRESETS[args.preset], seed=args.seed,
        max_shard_bytes=args.shard_bytes,
    )
    print(
        f"wrote {total / 1e9:.2f} GB ({args.preset}-shaped) to {args.out} "
        f"in {time.monotonic() - t0:.1f}s"
    )


if __name__ == "__main__":
    main()
