"""Headline benchmark: continuous-batching decode throughput per chip.

Runs the serving engine (the ``provider: tpu`` data plane) on the real
device(s): concurrent requests continuously batched into one decode stream,
Llama-3-family architecture sized to the available HBM (``bench-1b``
~1.1B params bf16 on a single v5e chip; the 8B flagship needs the full
v5e-8 — or one chip with ``ACP_BENCH_QUANTIZE=int8``).

Prints ONE JSON line:
  {"metric": "decode_tok_s_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": N/1000}
vs_baseline is against BASELINE.md's >1,000 tok/s/chip north-star target.

Knobs (env): ACP_BENCH_PRESET, ACP_BENCH_REQUESTS, ACP_BENCH_MAX_TOKENS,
ACP_BENCH_PROMPT_LEN, ACP_BENCH_MAX_CTX, ACP_BENCH_BLOCK,
ACP_BENCH_KV_LAYOUT (slot|paged), ACP_BENCH_QUANTIZE (int8),
ACP_BENCH_DEADLINE_S (per-burst wall-clock cap; partial results are
reported honestly), ACP_BENCH_DEVICE_TIMEOUT_S (device-probe watchdog),
ACP_BENCH_PROBE_WINDOW_S (tunnel retry window),
ACP_BENCH_TTFT=0 / ACP_BENCH_TTFT_TASKS / ACP_BENCH_TTFT_DEADLINE_S
(first-ToolCall latency phase), ACP_BENCH_AB=0 / ACP_BENCH_AB_BUDGET_S
(slot-vs-paged A/B leg).

If the accelerator cannot be reached within the watchdog window (e.g. a
wedged tunnel), prints value 0.0 with the failure on stderr rather than
hanging the driver.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _emit(value: float, note: str, extra: dict | None = None) -> None:
    doc = {
        "metric": "decode_tok_s_per_chip",
        "value": round(value, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(value / 1000.0, 3),
    }
    if extra:
        doc.update(extra)
    print(json.dumps(doc), flush=True)
    print(f"# {note}", file=sys.stderr, flush=True)


def _probe_devices(timeout_s: float):
    """jax.devices() in a watchdog thread — a wedged PJRT tunnel hangs it."""
    result: dict = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
        except Exception as e:  # pragma: no cover
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return None
    if "error" in result:
        raise result["error"]
    return result.get("devices")


def _wait_for_accelerator(attempt_timeout_s: float, window_s: float) -> bool:
    """Retry-with-backoff across the whole window using DISPOSABLE probe
    subprocesses, so a wedged axon tunnel never taints this process's PJRT
    client. Each probe is a fresh ``python -c "import jax; jax.devices()"``
    under a timeout; on success the main process can safely init jax."""
    import subprocess

    deadline = time.monotonic() + window_s
    attempt = 0
    while True:
        attempt += 1
        try:
            out = subprocess.run(
                [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
                capture_output=True,
                timeout=attempt_timeout_s,
                text=True,
            )
            if out.returncode == 0 and out.stdout.strip():
                print(
                    f"# probe attempt {attempt}: {out.stdout.strip().splitlines()[-1]} device(s)",
                    file=sys.stderr, flush=True,
                )
                return True
        except subprocess.TimeoutExpired:
            pass
        remaining = deadline - time.monotonic()
        print(
            f"# probe attempt {attempt} failed; {remaining:.0f}s left in retry window",
            file=sys.stderr, flush=True,
        )
        if remaining <= 30:
            return False
        time.sleep(30)


def _already_configured() -> bool:
    """True when this process has already decided its jax platform — the CPU
    smoke run (verify skill: jax_platforms forced to cpu before runpy) or a
    live initialized backend. NOTE: ``"jax" in sys.modules`` is NOT the
    right check in this image — the harness preimports jax into every
    Python process, which silently skipped the whole wedge-resistant probe
    path (round 1's instant 0.0 failure mode)."""
    if "jax" not in sys.modules:
        return False
    import jax

    try:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            return True  # a backend is already live; probing is moot
    except Exception:
        pass
    try:
        plats = jax.config.jax_platforms
    except Exception:
        return False
    return bool(plats) and "cpu" in str(plats)


def main() -> None:
    preset = os.environ.get("ACP_BENCH_PRESET", "bench-1b")
    n_requests = int(os.environ.get("ACP_BENCH_REQUESTS", "64"))
    max_tokens = int(os.environ.get("ACP_BENCH_MAX_TOKENS", "64"))
    prompt_len = int(os.environ.get("ACP_BENCH_PROMPT_LEN", "128"))
    max_ctx = int(os.environ.get("ACP_BENCH_MAX_CTX", "512"))
    block = int(os.environ.get("ACP_BENCH_BLOCK", "16"))
    kv_layout = os.environ.get("ACP_BENCH_KV_LAYOUT", "slot")
    quantize = os.environ.get("ACP_BENCH_QUANTIZE") or None
    deadline_s = float(os.environ.get("ACP_BENCH_DEADLINE_S", "420"))
    probe_timeout = float(os.environ.get("ACP_BENCH_DEVICE_TIMEOUT_S", "120"))

    window_s = float(os.environ.get("ACP_BENCH_PROBE_WINDOW_S", "600"))
    already_configured = _already_configured()
    # one wall-clock deadline across re-execs (see below): a wedged tunnel
    # can clear minutes later, but a hung in-process attach taints THIS
    # process forever, so retries need a fresh process image
    deadline_env = os.environ.get("ACP_BENCH_ATTACH_DEADLINE")
    attach_deadline = float(deadline_env) if deadline_env else time.time() + window_s
    probe_window = max(60.0, attach_deadline - time.time())
    if not already_configured and not _wait_for_accelerator(
        min(probe_timeout, 60.0), probe_window
    ):
        _emit(
            0.0,
            f"FAILED: accelerator unreachable across {probe_window:.0f}s of the "
            f"{window_s:.0f}s retry window (wedged tunnel?)",
        )
        return
    devices = _probe_devices(probe_timeout)
    if devices is None:
        if not already_configured and time.time() < attach_deadline - 90:
            print(
                f"# in-process attach hung ({probe_timeout:.0f}s); re-exec for a "
                f"fresh attempt, {attach_deadline - time.time():.0f}s left",
                file=sys.stderr, flush=True,
            )
            env = dict(os.environ)
            env["ACP_BENCH_ATTACH_DEADLINE"] = str(attach_deadline)
            sys.stderr.flush()
            sys.stdout.flush()
            os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)
        _emit(0.0, f"FAILED: accelerator probe ok but jax.devices() hung within {probe_timeout:.0f}s")
        return
    n_chips = len(devices)
    bench_t0 = time.monotonic()

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import serving_mesh

    import dataclasses

    config = PRESETS[preset]
    if config.max_seq_len < max_ctx:  # small presets (tiny) honor the knob
        config = dataclasses.replace(config, max_seq_len=max_ctx)
    ttft_on = os.environ.get("ACP_BENCH_TTFT", "1") != "0"

    def build_engine(layout: str):
        eng = Engine(
            config=config,
            tokenizer=ByteTokenizer(),
            mesh=serving_mesh(),
            max_slots=n_requests,
            max_ctx=max_ctx,
            prefill_buckets=(prompt_len, max_ctx),
            decode_block_size=block,
            kv_layout=layout,
            quantize=quantize,
            seed=0,
        )
        if ttft_on:
            # build the constraint token table up front so EVERY program in
            # this process (headline warm included) traces against the real
            # table shape — otherwise the TTFT phase's table build would
            # orphan the dummy-shaped compiles the headline phase paid for
            eng._get_token_table()
        eng.start()
        return eng

    prompt = [1 + (i % 250) for i in range(prompt_len - 1)]
    sampling = SamplingParams(temperature=0.8, top_p=0.95, max_tokens=max_tokens)

    def measure(
        eng, deadline_s: float = deadline_s, warm_timeout: float = 600.0
    ) -> tuple[float, int, float, int]:
        """Warmup (compiles every jit entry the burst hits: batched prefill
        chunks, max-width decode, the narrow decay widths) then the measured
        full-width burst. Returns (tok/s/chip, tokens, elapsed, done)."""
        warm = [
            eng.submit(list(prompt), SamplingParams(temperature=0.0, max_tokens=block + 1))
            for _ in range(n_requests)
        ]
        warm_deadline = time.monotonic() + warm_timeout
        for f in warm:
            f.result(timeout=max(1.0, warm_deadline - time.monotonic()))
        t0 = time.monotonic()
        toks0 = eng.tokens_generated
        futures = [eng.submit(list(prompt), sampling) for _ in range(n_requests)]
        deadline = t0 + deadline_s
        done = 0
        for f in futures:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                f.result(timeout=remaining)
                done += 1
            except Exception:
                break
        elapsed = time.monotonic() - t0
        total = eng.tokens_generated - toks0
        # drain leftovers so the next phase measures an idle engine
        for f in futures:
            eng.cancel(f)
        drain_deadline = time.monotonic() + 120
        while time.monotonic() < drain_deadline:
            s = eng.stats()
            if s["active_slots"] == 0 and s["waiting"] == 0:
                break
            time.sleep(0.2)
        return (total / elapsed) / max(n_chips, 1), total, elapsed, done

    engine = build_engine(kv_layout)
    tok_s_chip, total_tokens, elapsed, done = measure(engine)
    note = (
        f"{total_tokens} tokens in {elapsed:.2f}s on {n_chips} chip(s); preset={preset} "
        f"kv={kv_layout} quant={quantize or 'bf16'} block={block}; "
        f"{done}/{n_requests} requests completed"
        + ("" if done == n_requests else " (deadline hit; partial but honest)")
    )

    extra: dict = {}
    if ttft_on:
        try:
            extra["ttft_first_toolcall_ms"] = _bench_ttft(engine)
        except Exception as e:  # TTFT failure must not lose the headline number
            extra["ttft_error"] = str(e)
    engine.stop()
    del engine  # free weights+KV HBM before building the A/B engine

    # slot-vs-paged A/B: re-run the same burst against the other KV layout
    # and record which wins (VERDICT r1 #2). Budgeted: never runs past
    # ACP_BENCH_AB_BUDGET_S of total bench wall time, so a slow first phase
    # can't push the headline emit past the driver's patience.
    ab_budget = float(os.environ.get("ACP_BENCH_AB_BUDGET_S", "900"))
    spent = time.monotonic() - bench_t0
    remaining = ab_budget - spent
    # approximately bounded: warmup and the measured burst each get a
    # quarter of the remaining budget, the drain adds <=120s; engine-build
    # compile time is the one unbounded piece (first build of this layout)
    if os.environ.get("ACP_BENCH_AB", "1") != "0" and remaining > 240:
        other = "paged" if kv_layout == "slot" else "slot"
        try:
            eng2 = build_engine(other)
            ab_tok_s, ab_total, ab_elapsed, ab_done = measure(
                eng2,
                deadline_s=min(deadline_s, remaining / 4),
                warm_timeout=max(60.0, remaining / 4),
            )
            eng2.stop()
            extra[f"{other}_tok_s_per_chip"] = round(ab_tok_s, 1)
            extra["kv_layout_winner"] = (
                kv_layout if tok_s_chip >= ab_tok_s else other
            )
            print(
                f"# A/B {other}: {ab_total} tokens in {ab_elapsed:.2f}s "
                f"({ab_done}/{n_requests} done)",
                file=sys.stderr, flush=True,
            )
        except Exception as e:
            extra["ab_error"] = str(e)
    elif remaining <= 240:
        extra["ab_skipped"] = (
            f"only {remaining:.0f}s of ACP_BENCH_AB_BUDGET_S left after {spent:.0f}s"
        )
    _emit(tok_s_chip, note, extra or None)


def _bench_ttft(engine) -> dict:
    """BASELINE's second metric: p50/p95 task-create -> first-ToolCall-CR
    through the REAL operator with provider: tpu (configs 1+5 shape).
    tool_choice "required" teacher-forces the tool-call envelope so a
    random-weights model still produces a parseable ToolCall every time."""
    import asyncio

    from agentcontrolplane_tpu.api import ObjectMeta
    from agentcontrolplane_tpu.api.resources import (
        LLM, BaseConfig, LLMSpec, TPUProviderConfig,
    )
    from agentcontrolplane_tpu.engine.engine import SamplingParams
    from agentcontrolplane_tpu.operator import Operator, OperatorOptions
    from tests.fixtures import make_agent, make_task, setup_with_status

    n_tasks = int(os.environ.get("ACP_BENCH_TTFT_TASKS", "16"))
    preset = os.environ.get("ACP_BENCH_PRESET", "bench-1b")
    if engine.max_ctx < 256:
        # the rendered system+tools prompt plus the forced tool-call envelope
        # can't fit; the generation would hit max_ctx before closing the JSON
        return {"skipped": f"engine max_ctx {engine.max_ctx} < 256", "n": 0}

    # compile every program the staggered operator traffic will hit (token
    # table, every prefill bucket x batch size, every decode width) OUTSIDE
    # the measured window. The previous ad-hoc warm here missed the
    # mid-size batches and narrow widths that staggered reconcile arrivals
    # produce — each miss was a 20-40s tunnel compile COUNTED INTO TTFT
    # (r1's 41s p50 was compile stalls, not serving latency).
    engine.prewarm(constrained=True)

    async def run() -> dict:
        op = Operator(
            options=OperatorOptions(
                enable_rest=False, llm_probe=False,
                verify_channel_credentials=False, engine=engine,
            ),
        )
        op.task_reconciler.requeue_delay = 0.02
        op.toolcall_reconciler.poll_interval = 0.02
        store = op.store
        setup_with_status(
            store,
            LLM(
                metadata=ObjectMeta(name="tpu-llm"),
                spec=LLMSpec(
                    provider="tpu",
                    # tight tool-call budget: the grammar's budget-aware
                    # closure always yields a COMPLETE JSON object within
                    # max_tokens, and time-to-first-ToolCall includes the
                    # whole generation — every extra token is pure latency
                    parameters=BaseConfig(
                        model=preset,
                        max_tokens=int(os.environ.get("ACP_BENCH_TTFT_MAX_TOKENS", "24")),
                        temperature=0.7,
                    ),
                    tpu=TPUProviderConfig(preset=preset),
                    provider_config={"tool_choice": "required"},
                ),
            ),
            lambda o: (
                setattr(o.status, "ready", True),
                setattr(o.status, "status", "Ready"),
            ),
        )
        make_agent(store, name="leaf", llm="tpu-llm", system="leaf")
        make_agent(store, name="rooter", llm="tpu-llm", system="use tools",
                   sub_agents=("leaf",))
        await op.start()
        watch = store.watch("ToolCall")
        created: dict[str, float] = {}
        ttfts: list[float] = []
        try:
            for i in range(n_tasks):
                name = f"ttft-{i}"
                created[name] = time.monotonic()
                make_task(store, name=name, agent="rooter", user_message=f"task {i}")
            deadline = time.monotonic() + float(
                os.environ.get("ACP_BENCH_TTFT_DEADLINE_S", "240")
            )
            while len(ttfts) < n_tasks and time.monotonic() < deadline:
                ev = await watch.next(timeout=deadline - time.monotonic())
                if ev is None:
                    break
                if ev.type != "ADDED":
                    continue
                task_name = ev.object.metadata.labels.get("acp.tpu/task", "")
                if task_name in created:
                    ttfts.append((time.monotonic() - created.pop(task_name)) * 1e3)
        finally:
            watch.stop()
            await op.stop()
        if not ttfts:
            return {"error": "no ToolCalls observed", "n": 0}
        ttfts.sort()
        pick = lambda q: ttfts[min(len(ttfts) - 1, int(q * len(ttfts)))]
        return {
            "p50": round(pick(0.50), 1),
            "p95": round(pick(0.95), 1),
            "n": len(ttfts),
            "target_ms": 500,
        }

    return asyncio.run(run())


if __name__ == "__main__":
    main()
