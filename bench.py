"""Headline benchmark: continuous-batching decode throughput per chip.

Runs the serving engine (the ``provider: tpu`` data plane) on the real
device(s): 64 concurrent requests continuously batched into one decode
stream, Llama-3-family architecture sized to the available HBM
(``bench-1b`` ~1.1B params bf16 on a single v5e chip; the 8B flagship
needs the full v5e-8 and loads the same way).

Prints ONE JSON line:
  {"metric": "decode_tok_s_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": N/1000}
vs_baseline is against BASELINE.md's >1,000 tok/s/chip north-star target.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> None:
    import jax

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import serving_mesh

    preset = os.environ.get("ACP_BENCH_PRESET", "bench-1b")
    n_requests = int(os.environ.get("ACP_BENCH_REQUESTS", "64"))
    max_tokens = int(os.environ.get("ACP_BENCH_MAX_TOKENS", "128"))
    prompt_len = int(os.environ.get("ACP_BENCH_PROMPT_LEN", "128"))
    max_ctx = int(os.environ.get("ACP_BENCH_MAX_CTX", "1024"))

    n_chips = len(jax.devices())
    config = PRESETS[preset]
    engine = Engine(
        config=config,
        tokenizer=ByteTokenizer(),
        mesh=serving_mesh(),
        max_slots=n_requests,
        max_ctx=max_ctx,
        prefill_buckets=(prompt_len, max_ctx),
        seed=0,
    )
    engine.start()

    prompt = list(range(1, prompt_len))  # token ids, avoids tokenizer cost
    sampling = SamplingParams(temperature=0.8, top_p=0.95, max_tokens=max_tokens)

    # warmup: compile prefill + decode
    engine.generate(prompt[:prompt_len], SamplingParams(temperature=0.0, max_tokens=4))

    t0 = time.monotonic()
    steps0, toks0 = engine.decode_steps, engine.tokens_generated
    futures = [engine.submit(list(prompt), sampling) for _ in range(n_requests)]
    results = [f.result(timeout=1200) for f in futures]
    elapsed = time.monotonic() - t0
    engine.stop()

    total_tokens = sum(len(r.tokens) for r in results)
    tok_s = total_tokens / elapsed
    tok_s_chip = tok_s / n_chips
    ttfts = sorted(r.ttft_ms for r in results)
    p50_ttft = ttfts[len(ttfts) // 2]

    print(
        json.dumps(
            {
                "metric": "decode_tok_s_per_chip",
                "value": round(tok_s_chip, 1),
                "unit": "tok/s/chip",
                "vs_baseline": round(tok_s_chip / 1000.0, 3),
            }
        )
    )
    print(
        f"# {total_tokens} tokens in {elapsed:.2f}s on {n_chips} chip(s) "
        f"({preset}); total {tok_s:.0f} tok/s; p50 TTFT {p50_ttft:.0f} ms "
        f"(includes queue wait at {n_requests}-deep burst)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
