"""Headline benchmark: continuous-batching decode throughput per chip.

Runs the serving engine (the ``provider: tpu`` data plane) on the real
device(s): concurrent requests continuously batched into one decode stream,
Llama-3-family architecture sized to the available HBM (``bench-1b``
~1.1B params bf16 on a single v5e chip; the 8B flagship needs the full
v5e-8 — or one chip with ``ACP_BENCH_QUANTIZE=int8``).

Prints ONE JSON line:
  {"metric": "decode_tok_s_per_chip", "value": N, "unit": "tok/s/chip",
   "vs_baseline": N/1000}
vs_baseline is against BASELINE.md's >1,000 tok/s/chip north-star target.

Knobs (env): ACP_BENCH_PRESET, ACP_BENCH_REQUESTS, ACP_BENCH_MAX_TOKENS,
ACP_BENCH_PROMPT_LEN, ACP_BENCH_MAX_CTX, ACP_BENCH_BLOCK,
ACP_BENCH_KV_LAYOUT (slot|paged), ACP_BENCH_QUANTIZE (int8),
ACP_BENCH_DEADLINE_S (wall-clock cap; partial results are reported
honestly), ACP_BENCH_DEVICE_TIMEOUT_S (device-probe watchdog).

If the accelerator cannot be reached within the watchdog window (e.g. a
wedged tunnel), prints value 0.0 with the failure on stderr rather than
hanging the driver.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _emit(value: float, note: str) -> None:
    print(
        json.dumps(
            {
                "metric": "decode_tok_s_per_chip",
                "value": round(value, 1),
                "unit": "tok/s/chip",
                "vs_baseline": round(value / 1000.0, 3),
            }
        ),
        flush=True,
    )
    print(f"# {note}", file=sys.stderr, flush=True)


def _probe_devices(timeout_s: float):
    """jax.devices() in a watchdog thread — a wedged PJRT tunnel hangs it."""
    result: dict = {}

    def probe():
        try:
            import jax

            result["devices"] = jax.devices()
        except Exception as e:  # pragma: no cover
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return None
    if "error" in result:
        raise result["error"]
    return result.get("devices")


def main() -> None:
    preset = os.environ.get("ACP_BENCH_PRESET", "bench-1b")
    n_requests = int(os.environ.get("ACP_BENCH_REQUESTS", "64"))
    max_tokens = int(os.environ.get("ACP_BENCH_MAX_TOKENS", "64"))
    prompt_len = int(os.environ.get("ACP_BENCH_PROMPT_LEN", "128"))
    max_ctx = int(os.environ.get("ACP_BENCH_MAX_CTX", "512"))
    block = int(os.environ.get("ACP_BENCH_BLOCK", "16"))
    kv_layout = os.environ.get("ACP_BENCH_KV_LAYOUT", "slot")
    quantize = os.environ.get("ACP_BENCH_QUANTIZE") or None
    deadline_s = float(os.environ.get("ACP_BENCH_DEADLINE_S", "420"))
    probe_timeout = float(os.environ.get("ACP_BENCH_DEVICE_TIMEOUT_S", "120"))

    devices = _probe_devices(probe_timeout)
    if devices is None:
        _emit(0.0, f"FAILED: accelerator unreachable within {probe_timeout:.0f}s (wedged tunnel?)")
        return
    n_chips = len(devices)

    from agentcontrolplane_tpu.engine.engine import Engine, SamplingParams
    from agentcontrolplane_tpu.engine.tokenizer import ByteTokenizer
    from agentcontrolplane_tpu.models.llama import PRESETS
    from agentcontrolplane_tpu.parallel.mesh import serving_mesh

    engine = Engine(
        config=PRESETS[preset],
        tokenizer=ByteTokenizer(),
        mesh=serving_mesh(),
        max_slots=n_requests,
        max_ctx=max_ctx,
        prefill_buckets=(prompt_len, max_ctx),
        decode_block_size=block,
        kv_layout=kv_layout,
        quantize=quantize,
        seed=0,
    )
    engine.start()
    prompt = [1 + (i % 250) for i in range(prompt_len - 1)]
    sampling = SamplingParams(temperature=0.8, top_p=0.95, max_tokens=max_tokens)

    # warmup: compile prefill + decode block
    engine.generate(prompt, SamplingParams(temperature=0.0, max_tokens=block + 1))

    t0 = time.monotonic()
    toks0 = engine.tokens_generated
    futures = [engine.submit(list(prompt), sampling) for _ in range(n_requests)]
    deadline = t0 + deadline_s
    done = 0
    for f in futures:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        try:
            f.result(timeout=remaining)
            done += 1
        except Exception:
            break
    elapsed = time.monotonic() - t0
    total_tokens = engine.tokens_generated - toks0
    engine.stop()

    tok_s_chip = (total_tokens / elapsed) / max(n_chips, 1)
    note = (
        f"{total_tokens} tokens in {elapsed:.2f}s on {n_chips} chip(s); preset={preset} "
        f"kv={kv_layout} quant={quantize or 'bf16'} block={block}; "
        f"{done}/{n_requests} requests completed"
        + ("" if done == n_requests else " (deadline hit; partial but honest)")
    )
    _emit(tok_s_chip, note)


if __name__ == "__main__":
    main()
